"""Front-door stream router for the decode fleet (``pst-route``).

The router speaks the SAME ``psdt_fleet.Decode`` service it routes to —
a client cannot tell a router from a single decode server, which is the
downgrade matrix: no router => point ``pst-serve`` clients at the one
server, byte-unchanged.

Admission: each incoming ``SubmitStream`` picks the best ACTIVE backend
by **free-slot / queue-depth score plus cached-prefix overlap** (free
slots plus ``PSDT_ROUTE_OVERLAP_WEIGHT`` per leading prompt block
already in the backend's radix prefix cache — fingerprints ride the
``UpdateFleet`` heartbeats, models/prefix_tree.py — then shortest
queue tie-break, server id as the stable final tie-break) from the
coordinator's fleet table (TTL-polled over ``UpdateFleet``; the router
additionally debits a claim per stream it routed since the last poll,
so a burst between polls spreads instead of dogpiling the
momentarily-best server).  Backends without a fingerprint (cache off,
pre-radix builds) score zero overlap, so the order degrades to exactly
the PR 14 free-slot score.  The stream is then **pinned**: every chunk of
its lifetime relays from that one backend — a mid-stream weight rollout
on the backend swaps the version under the stream (PR 10 semantics, the
tokens keep flowing), and the router never re-routes a live
continuation, which is what makes rolling updates zero-drop.

DRAINING backends take no new streams but keep their pinned ones; a
backend that dies mid-stream surfaces as that stream's error chunk
(the decode context is gone — re-routing a continuation would silently
restart the generation)."""

from __future__ import annotations

import logging
import threading
import time

import grpc

import os

from ..analysis.lock_order import checked_lock
from ..models.prefix_tree import block_hashes, overlap_blocks, unpack_fp
from ..obs import flight
from ..obs import stats as obs_stats
from ..rpc import messages as m
from ..rpc.service import RpcClient, make_server
from ..rpc.service import status_code as _status_code
from . import messages as fmsg

log = logging.getLogger("pst.fleet.router")


def overlap_weight() -> float:
    """Free-slot-equivalents one reusable prefix block is worth in the
    routing score (``PSDT_ROUTE_OVERLAP_WEIGHT``): cache affinity may
    outbid up to ``weight * blocks`` free slots, never an infinite
    amount — a backend with a hot prefix but a long queue still loses
    to an idle one eventually.  0 disables prefix-aware routing."""
    return float(os.environ.get("PSDT_ROUTE_OVERLAP_WEIGHT", "1.0"))


def score_backends(entries, claims: dict[int, int] | None = None,
                   prompt_hashes=None, weight: float = 1.0) -> list:
    """ACTIVE backends ordered best-first: free slots (minus the
    router's own un-heartbeaten claims) PLUS cached-prefix overlap —
    each leading block of the prompt already in a backend's radix cache
    (``prompt_hashes`` vs the entry's heartbeated ``prefix_fp``) counts
    as ``weight`` free slots — then shortest queue, then server id.
    Pure — the unit-testable policy.  Without prompt hashes, or against
    entries with no fingerprint (cache off, pre-radix builds), every
    overlap is zero and the order is exactly the PR 14 free-slot/
    queue-depth score (the downgrade matrix)."""
    claims = claims or {}
    live = [e for e in entries if int(e.state) == fmsg.MEMBER_ACTIVE]

    def affinity(e) -> float:
        fp = bytes(getattr(e, "prefix_fp", b""))
        if not prompt_hashes or not fp or not weight:
            return 0.0
        return weight * overlap_blocks(prompt_hashes, unpack_fp(fp))

    return sorted(
        live,
        key=lambda e: (-(int(e.free_slots)
                         - claims.get(int(e.server_id), 0)
                         + affinity(e)),
                       int(e.queue_depth), int(e.server_id)))


class FleetRouter:
    """See module docstring."""

    def __init__(self, coordinator: str, *, port: int = 0,
                 bind_address: str = "127.0.0.1",
                 poll_s: float = 0.5):
        self._coordinator = coordinator
        self._bind = f"{bind_address}:{int(port)}"
        self._poll_s = float(poll_s)
        # Guards the backend table, per-backend claims, the backend
        # client cache, and the poll-in-flight flag (leaf —
        # analysis/lock_order.py rank 75).
        self._lock = checked_lock("FleetRouter._lock")
        # Poll single-flight is a FLAG under _lock, not a lock held
        # across the RPC: while one thread refreshes, every other
        # admission routes on the last-known table + claims instead of
        # queueing behind a coordinator round-trip (a slow coordinator
        # would otherwise add its full RPC timeout to fleet-wide TTFT).
        self._polling = False
        self._entries: list = []
        self._table_at = 0.0
        self._epoch = 0
        self._claims: dict[int, int] = {}
        self._clients: dict[str, RpcClient] = {}
        self._next_stream = 0
        self.streams_routed = 0
        self._obs_routed = obs_stats.counter("fleet.routed")
        self._obs_rejected = obs_stats.counter("fleet.route_rejected")
        self._obs_backends = obs_stats.gauge("fleet.route_backends")
        # prefix blocks of the last routed prompt already cached on the
        # chosen backend (0 = no reusable prefix / fingerprints absent)
        self._obs_overlap = obs_stats.gauge("fleet.route_overlap")
        self._coord = RpcClient(coordinator, m.COORDINATOR_SERVICE,
                                fmsg.FLEET_COORD_METHODS)
        self._grpc = None
        self.port = 0

    # ----------------------------------------------------------- lifecycle
    def start(self) -> int:
        from ..rpc.service import bind_service
        self._grpc = make_server(max_workers=32)
        bind_service(self._grpc, fmsg.DECODE_SERVICE, fmsg.DECODE_METHODS,
                     self)
        self.port = self._grpc.add_insecure_port(self._bind)
        if self.port == 0:
            raise RuntimeError(f"could not bind {self._bind}")
        self._grpc.start()
        return self.port

    def stop(self, grace: float = 1.0) -> None:
        if self._grpc is not None:
            self._grpc.stop(grace).wait()
        with self._lock:
            clients, self._clients = dict(self._clients), {}
        for client in clients.values():
            client.close()
        self._coord.close()

    def wait(self) -> None:
        assert self._grpc is not None
        self._grpc.wait_for_termination()

    # ------------------------------------------------------------- routing
    def _refresh_table(self, force: bool = False) -> None:
        """TTL refresh of the fleet table.  Non-blocking for everyone
        but the one thread that actually polls: a stale-but-usable
        table plus claims beats queueing admissions behind a
        coordinator RPC.  ``force`` polls even when fresh (the
        empty-table retry and the Control STATUS probe) but still
        yields to a poll already in flight."""
        with self._lock:
            fresh = (time.monotonic() - self._table_at < self._poll_s)
            if (fresh and not force) or self._polling:
                return
            self._polling = True
        try:
            resp = self._coord.call(
                "UpdateFleet",
                fmsg.FleetRequest(server_id=-1,
                                  action=fmsg.FLEET_QUERY),
                timeout=2.0)
        except grpc.RpcError as exc:
            if _status_code(exc) == grpc.StatusCode.UNIMPLEMENTED:
                log.warning("coordinator does not speak UpdateFleet; "
                            "router has no fleet to route to")
            return  # transient: keep the last table
        finally:
            with self._lock:
                self._polling = False
        with self._lock:
            self._entries = list(resp.entries)
            self._epoch = int(resp.epoch)
            self._table_at = time.monotonic()
            self._claims.clear()  # the table now reflects past claims
            self._obs_backends.set(sum(
                1 for e in self._entries
                if int(e.state) == fmsg.MEMBER_ACTIVE))

    def _pick_backend(self, prompt_tokens=None):
        """Best backend entry or None.  Debits a claim so concurrent
        admissions between polls spread across the fleet.  An empty
        view retries briefly (force-polling, yielding to a poll already
        in flight) before rejecting — a cold router's second concurrent
        admission must not bounce just because the first one's table
        poll has not landed yet.  ``prompt_tokens`` turns on
        prefix-aware placement: the prompt's block hashes are scored
        against each backend's heartbeated radix fingerprint, so
        streams sharing a system prompt pin to the backend already
        holding it."""
        hashes = block_hashes(prompt_tokens) if prompt_tokens else None
        weight = overlap_weight()
        self._refresh_table()
        deadline = time.monotonic() + 2.0
        while True:
            with self._lock:
                ranked = score_backends(self._entries, self._claims,
                                        hashes, weight)
                if ranked:
                    best = ranked[0]
                    sid = int(best.server_id)
                    self._claims[sid] = self._claims.get(sid, 0) + 1
                    if hashes:
                        self._obs_overlap.set(overlap_blocks(
                            hashes, unpack_fp(bytes(
                                getattr(best, "prefix_fp", b"")))))
                    return best
            if time.monotonic() >= deadline:
                return None
            time.sleep(0.05)
            self._refresh_table(force=True)

    def _backend_client(self, address: str) -> RpcClient:
        with self._lock:
            client = self._clients.get(address)
            if client is None:
                client = RpcClient(address, fmsg.DECODE_SERVICE,
                                   fmsg.DECODE_METHODS)
                self._clients[address] = client
            return client

    # ---------------------------------------------------------------- gRPC
    def SubmitStream(self, request: fmsg.DecodeRequest, context):
        backend = self._pick_backend([int(t) for t in request.tokens])
        if backend is None:
            self._obs_rejected.add()
            yield fmsg.DecodeChunk(error="no decode servers available",
                                   done=True)
            return
        with self._lock:
            self._next_stream += 1
            stream_id = self._next_stream
        sid = int(backend.server_id)
        flight.record("fleet.route", a=stream_id, b=sid,
                      note=backend.address[:48])
        self.streams_routed += 1
        self._obs_routed.add()
        client = self._backend_client(backend.address)
        try:
            # pinned for the stream's lifetime: every chunk relays from
            # this one backend, mid-rollout swaps included
            for chunk in client.call("SubmitStream", request,
                                     timeout=None):
                yield chunk
                if chunk.done:
                    return
        except grpc.RpcError as exc:
            # the backend died mid-stream: its decode context is gone,
            # so the honest answer is an error, not a silent restart
            self._obs_rejected.add()
            yield fmsg.DecodeChunk(
                error=f"backend {sid} lost mid-stream "
                      f"({_status_code(exc)})", done=True)

    def Control(self, request: fmsg.DecodeControlRequest,
                context) -> fmsg.DecodeControlResponse:
        """The router's own status: backends visible, streams routed.
        Management actions target servers, not the router."""
        if int(request.action) != fmsg.CTRL_STATUS:
            return fmsg.DecodeControlResponse(
                success=False,
                message="router: only STATUS is supported here; address "
                        "Control to a decode server")
        self._refresh_table()
        with self._lock:
            active = [e for e in self._entries
                      if int(e.state) == fmsg.MEMBER_ACTIVE]
            return fmsg.DecodeControlResponse(
                success=True,
                message=f"router: {len(active)} active backends "
                        f"(fleet epoch {self._epoch})",
                server_id=-1,
                slots=sum(int(e.slots) for e in active),
                free_slots=sum(int(e.free_slots) for e in active),
                queue_depth=sum(int(e.queue_depth) for e in active),
                streams_served=self.streams_routed)
