"""Decode-fleet extension RPC messages (ISSUE 14).

Deliberately NOT in ``rpc/messages.py``: the analyzer's wire manifest
pins the reference contract and the fleet subsystem must leave it
byte-unchanged (asserted in tests/test_analysis.py).  Two surfaces:

- **``UpdateFleet``** — an extra method name on the existing coordinator
  gRPC service, the serving twin of elastic/'s ``UpdateMembership``: one
  RPC registers a DecodeServer, refreshes its load heartbeat (free
  slots, queue depth, weight version), announces a graceful leave,
  requests a drain, sets the manual scale target, and queries the
  epoch-numbered fleet table.  A reference coordinator answers
  UNIMPLEMENTED => the decode process keeps serving standalone (the
  PR-2/PR-13 permanent-downgrade discipline).
- **the decode service** (``psdt_fleet.Decode``) — a NEW gRPC service
  name (no reference collision possible): ``SubmitStream`` carries one
  request in and streams its tokens back (each chunk stamped with the
  weight version that decoded it — the version-skew evidence the router
  tests pin), and ``Control`` is the fleet-management side door (status
  probe, rolling weight swap, rollback-to-pinned-version, drain).  The
  router speaks ``SubmitStream`` on BOTH faces, so a client cannot tell
  a router from a single server.

Fleet member states reuse the elastic membership constants
(JOINING/ACTIVE/DRAINING/GONE — :mod:`..elastic.messages`): scale-in IS
the PR 13 drain-before-stop path, applied to serving processes.
"""

from __future__ import annotations

from ..elastic.messages import (MEMBER_ACTIVE, MEMBER_DRAINING,  # noqa: F401
                                MEMBER_GONE, MEMBER_JOINING, STATE_NAMES)
from ..rpc.messages import TRACE_FIELD_NUMBER
from ..rpc.wire import Field, Message

# UpdateFleet actions.  Append-only: values ride the wire.
FLEET_QUERY = 0      # pure read (router poll, pst-ctl fleet)
FLEET_REGISTER = 1   # decode server announces itself (JOINING -> ACTIVE)
FLEET_HEARTBEAT = 2  # load refresh: free slots / queue depth / version
FLEET_LEAVE = 3      # graceful leave (drain completed / shutdown)
FLEET_DRAIN = 4      # mark target_server_id DRAINING (scale-in, pst-ctl)
FLEET_SCALE = 5      # set the manual scale target (0 = autoscale)

# Control actions on the decode service.
CTRL_STATUS = 0      # status probe (no side effect)
CTRL_SWAP = 1        # swap to held version `version` (-1 = newest held)
CTRL_ROLLBACK = 2    # swap BACK to `version` and pin there: no newer
                     # version may serve a continuation until CTRL_UNPIN
CTRL_UNPIN = 3       # clear the rollback pin (auto/rolling swaps resume)
CTRL_DRAIN = 4       # stop admitting, finish in-flight streams, leave


class FleetEntry(Message):
    """One decode server's fleet row: identity, capacity, the load
    signals the router scores on, and the weight version it serves.
    ``prefix_fp`` is the server's radix prefix-cache fingerprint
    (packed chained-CRC32 block hashes — models/prefix_tree.py); empty
    from servers without a prefix cache (or older builds), in which
    case the router's overlap term is zero and scoring degrades to the
    PR 14 free-slot/queue-depth order."""
    FIELDS = (
        Field(1, "server_id", "int32"),
        Field(2, "address", "string"),
        Field(3, "slots", "int32"),
        Field(4, "free_slots", "int32"),
        Field(5, "queue_depth", "int32"),
        Field(6, "weight_version", "int32"),
        Field(7, "state", "int32"),
        Field(8, "epoch", "int32"),
        Field(9, "active_streams", "int32"),
        Field(10, "prefix_fp", "bytes"),
    )


class FleetRequest(Message):
    """Register-heartbeat-query in one RPC (see module docstring).
    ``target_server_id`` is read only for ``FLEET_DRAIN``;
    ``scale_target`` only for ``FLEET_SCALE``."""
    FIELDS = (
        Field(1, "server_id", "int32"),
        Field(2, "action", "int32"),
        Field(3, "address", "string"),
        Field(4, "slots", "int32"),
        Field(5, "free_slots", "int32"),
        Field(6, "queue_depth", "int32"),
        Field(7, "weight_version", "int32"),
        Field(8, "active_streams", "int32"),
        Field(9, "target_server_id", "int32"),
        Field(10, "scale_target", "int32"),
        Field(11, "prefix_fp", "bytes"),
        Field(TRACE_FIELD_NUMBER, "trace_context", "bytes"),
    )


class FleetResponse(Message):
    """``self_state`` answers the requesting server directly (the
    heartbeat-cadence drain poll needs only this field; -1 = unknown);
    ``scale_target`` echoes the manual target (0 = autoscale)."""
    FIELDS = (
        Field(1, "epoch", "int32"),
        Field(2, "success", "bool"),
        Field(3, "message", "string"),
        Field(4, "self_state", "int32"),
        Field(5, "entries", "message", message_type=FleetEntry,
              repeated=True),
        Field(6, "scale_target", "int32"),
    )


# --------------------------------------------------------- decode service
class DecodeRequest(Message):
    """One stream admission: the prompt as token ids, generation budget,
    and per-request sampling overrides (temperature < 0 = server
    default, matching DecodeServer.submit(temperature=None))."""
    FIELDS = (
        Field(1, "tokens", "int32", repeated=True),
        Field(2, "max_new", "int32"),
        Field(3, "temperature", "float"),
        Field(4, "stop", "int32", repeated=True),
        Field(TRACE_FIELD_NUMBER, "trace_context", "bytes"),
    )


class DecodeChunk(Message):
    """One streamed token (or the terminal chunk).  ``weight_version``
    stamps the params version that decoded THIS token — the router
    version-skew tests read it to prove a pinned rollback never serves
    a newer-version continuation.  ``error`` non-empty = the request
    failed (bad prompt, draining server); ``done`` closes the stream."""
    FIELDS = (
        Field(1, "request_id", "int32"),
        Field(2, "token", "int32"),
        Field(3, "done", "bool"),
        Field(4, "error", "string"),
        Field(5, "weight_version", "int32"),
    )


class DecodeControlRequest(Message):
    FIELDS = (
        Field(1, "action", "int32"),
        Field(2, "version", "int32"),
        Field(TRACE_FIELD_NUMBER, "trace_context", "bytes"),
    )


class DecodeControlResponse(Message):
    """The per-server status the controller and router poll: capacity,
    load, the serving version, held versions, and the rollback pin
    (-1 = unpinned)."""
    FIELDS = (
        Field(1, "success", "bool"),
        Field(2, "message", "string"),
        Field(3, "server_id", "int32"),
        Field(4, "state", "int32"),
        Field(5, "slots", "int32"),
        Field(6, "free_slots", "int32"),
        Field(7, "queue_depth", "int32"),
        Field(8, "weight_version", "int32"),
        Field(9, "pinned_version", "int32"),
        Field(10, "versions_held", "int32", repeated=True),
        Field(11, "streams_served", "int32"),
        # prompt-phase reuse accounting (ISSUE 20): tokens the prompt
        # phase actually forwarded vs prompt tokens admitted — the
        # fleet bench's prefill-computed/prompt ratio numerator and
        # denominator (0/0 from pre-radix builds)
        Field(12, "prefill_tokens", "int64"),
        Field(13, "prompt_tokens", "int64"),
    )


# Extra method on the existing coordinator service (extension — absent
# from the reference's method table and the pinned wire manifest).
FLEET_COORD_METHODS = {
    "UpdateFleet": (FleetRequest, FleetResponse),
}

# A NEW service name: the decode plane never shares a wire surface with
# the reference protocol.
DECODE_SERVICE = "psdt_fleet.Decode"
DECODE_METHODS = {
    "SubmitStream": (DecodeRequest, DecodeChunk, "unary_stream"),
    "Control": (DecodeControlRequest, DecodeControlResponse),
}
