"""Fleet-facing decode server: the gRPC face of models/serving.DecodeServer.

One :class:`FleetDecodeServer` wraps one continuous-batching
:class:`~..models.serving.DecodeServer` behind the ``psdt_fleet.Decode``
gRPC service and runs three loops:

- the **decode loop** (the ONLY thread that touches the DecodeServer):
  admits queued requests into free slots between ``step()`` rounds,
  streams each newly decoded token to its request's output queue, and
  applies weight swaps/commands at round boundaries — continuous
  batching under an open-loop arrival process, no drain-the-batch
  barrier anywhere;
- the **membership loop** (when a coordinator address is given):
  ``UpdateFleet`` register + heartbeat-cadence load reports (free
  slots, queue depth, serving version), which double as the drain
  signal — a coordinator-side drain (scale-in, ``pst-ctl``) is seen on
  the next beat, the server stops admitting, finishes its in-flight
  streams, and leaves.  A reference coordinator answers UNIMPLEMENTED
  => permanent standalone downgrade (the PR-2/PR-13 discipline);
- the optional **weight feed**: a :class:`~..delta.subscriber
  .WeightFollower` (PR 10) polled between rounds fills the bounded
  version store.  Standalone servers auto-advance to each version as it
  lands (exactly ``pst-serve --follow``); fleet-registered servers hold
  versions and swap when the controller says so (the rolling update),
  unless ``auto_advance`` is forced.

Version skew is first-class: every streamed chunk is stamped with the
params version that decoded it, ``Control(ROLLBACK, v)`` swaps back to a
held version AND pins there — publications newer than the pin are held
but never served until ``Control(UNPIN)`` — so a rolled-back fleet can
never leak a newer-version continuation (tested).
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from collections import OrderedDict
from typing import Callable

import grpc

from ..analysis.lock_order import checked_lock
from ..elastic import messages as emsg
from ..obs import flight
from ..obs import stats as obs_stats
from ..rpc import messages as m
from ..rpc.service import RpcClient, make_server
from ..rpc.service import status_code as _status_code
from . import messages as fmsg

log = logging.getLogger("pst.fleet.decode")

# Serializes jax dispatch across colocated decode servers (tests, bench,
# single-host fleets run several FleetDecodeServers in one process;
# concurrent dispatch deadlocks the CPU client — the same hazard
# worker/trainer.py's _DISPATCH_LOCK guards).  Uncontended when each
# server runs in its own process, which is the production shape.
_DISPATCH_LOCK = checked_lock("decode._DISPATCH_LOCK")


class _Stream:
    """One admitted (or queued) request: its parsed fields and the
    queue its chunks flow out on (None = end of stream).  ``cancelled``
    is set by the handler when the client is gone (disconnect, stall
    timeout): a cancelled stream is never admitted from the queue, and
    an in-flight one has its slot freed at the next round — an
    abandoned request must not burn max_new decode rounds into a queue
    nobody reads."""

    __slots__ = ("tokens", "max_new", "temperature", "stop", "out",
                 "request_id", "cancelled")

    def __init__(self, tokens, max_new, temperature, stop):
        self.tokens = tokens
        self.max_new = max_new
        self.temperature = temperature
        self.stop = stop
        self.out: "queue.Queue[fmsg.DecodeChunk | None]" = queue.Queue()
        self.request_id = -1
        self.cancelled = False


class _CommandBox:
    """Outcome channel for one decode-loop command: the Control handler
    waits on ``done`` and reads ``ok``/``why``."""

    __slots__ = ("done", "ok", "why")

    def __init__(self):
        self.done = threading.Event()
        self.ok = False
        self.why = ""


def box_ok(box: _CommandBox | None) -> None:
    if box is not None:
        box.ok = True
        box.done.set()


def box_fail(box: _CommandBox | None, why: str) -> None:
    if box is not None:
        box.why = why
        box.done.set()


class FleetDecodeServer:
    """See module docstring.  ``transform`` is applied to every published
    store before it swaps in (the int8 weight-quantization binding from
    cli/serve_main.py — boot weights and every fleet swap must quantize
    identically or not at all)."""

    def __init__(self, server, *, server_id: int = 0, port: int = 0,
                 bind_address: str = "127.0.0.1",
                 coordinator: str | None = None,
                 follower=None, auto_advance: bool | None = None,
                 transform: Callable[[dict], dict] | None = None,
                 versions_kept: int = 4, heartbeat_s: float = 0.5):
        self.server = server
        self.server_id = int(server_id)
        self._bind = f"{bind_address}:{int(port)}"
        self._coordinator = coordinator
        self._follower = follower
        self._transform = transform
        # standalone servers track the feed live (pst-serve --follow
        # semantics); fleet-registered ones hold versions for the
        # controller's rolling update
        self.auto_advance = (coordinator is None if auto_advance is None
                             else bool(auto_advance))
        self._versions_kept = max(1, int(versions_kept))
        self._heartbeat_s = float(heartbeat_s)
        # Synthetic per-round service time (netsim-style): the fleet
        # bench and scale tests pin it so per-server capacity is sleep-
        # bound instead of host-CPU-bound — a tiny CPU model on a 2-core
        # host would otherwise hide the control plane's scaling behind
        # the shared cores.  0 (default) = off, production shape.
        self._round_delay_s = float(
            os.environ.get("PSDT_DECODE_ROUND_DELAY_MS", "0")) / 1e3
        # Guards the version store, pin, command queue hand-off flags,
        # and stream bookkeeping shared between gRPC handler threads and
        # the decode loop (leaf — analysis/lock_order.py rank 74).
        self._lock = checked_lock("FleetDecodeServer._lock")
        self._versions: "OrderedDict[int, dict]" = OrderedDict()
        self._pinned = -1
        self._admit: "queue.Queue[_Stream]" = queue.Queue()
        self._live: dict[int, _Stream] = {}     # request_id -> stream
        self._commands: "queue.Queue[tuple]" = queue.Queue()
        self._wake = threading.Event()
        self._draining = False
        self._stopped = threading.Event()
        self._left = threading.Event()   # deregistered (drain complete)
        self._registered = False
        self.streams_served = 0
        self._obs_streams = obs_stats.counter("fleet.streams")
        self._obs_errors = obs_stats.counter("fleet.stream_errors")
        self._obs_swaps = obs_stats.counter("fleet.swaps")
        self._obs_queue = obs_stats.gauge("fleet.queue_depth")
        self._grpc: grpc.Server | None = None
        self.port = 0
        self._decode_thread = threading.Thread(
            target=self._decode_loop, daemon=True,
            name=f"fleet-decode-{server_id}")
        self._member_thread: threading.Thread | None = None
        self._client: RpcClient | None = None

    # ----------------------------------------------------------- lifecycle
    def start(self) -> int:
        """Bind the gRPC service, start the decode loop, and (with a
        coordinator) register + heartbeat.  Returns the bound port."""
        from ..rpc.service import bind_service
        self._grpc = make_server()
        bind_service(self._grpc, fmsg.DECODE_SERVICE, fmsg.DECODE_METHODS,
                     self)
        self.port = self._grpc.add_insecure_port(self._bind)
        if self.port == 0:
            raise RuntimeError(f"could not bind {self._bind}")
        self._grpc.start()
        self.address = f"{self._bind.rsplit(':', 1)[0]}:{self.port}"
        self._decode_thread.start()
        if self._coordinator:
            self._client = RpcClient(self._coordinator,
                                     m.COORDINATOR_SERVICE,
                                     fmsg.FLEET_COORD_METHODS)
            self._member_thread = threading.Thread(
                target=self._membership_loop, daemon=True,
                name=f"fleet-member-{self.server_id}")
            self._member_thread.start()
        return self.port

    def stop(self, grace: float = 1.0) -> None:
        self._stopped.set()
        self._wake.set()
        if self._grpc is not None:
            self._grpc.stop(grace).wait()
        self._decode_thread.join(timeout=5.0)
        if self._member_thread is not None:
            self._member_thread.join(timeout=5.0)
        if self._client is not None:
            self._client.close()
            self._client = None
        if self._follower is not None:
            self._follower.stop()

    def drain(self) -> None:
        """Stop admitting new streams; in-flight ones finish, then the
        server leaves the fleet (wait_drained() unblocks).  The SIGTERM
        and Control(DRAIN) path."""
        self._draining = True
        self._wake.set()

    def wait_drained(self, timeout: float | None = None) -> bool:
        """Block until a drain completed (in-flight streams finished and
        the server left the fleet) — the scale-in stop barrier."""
        return self._left.wait(timeout)

    @property
    def draining(self) -> bool:
        return self._draining

    # ------------------------------------------------------------- helpers
    def queue_depth(self) -> int:
        return self._admit.qsize()

    def free_slots(self) -> int:
        """Router-facing capacity: slots not yet claimed by an in-flight
        OR queued request (a queued admission claims its slot at the
        next round boundary — advertising it free would double-book)."""
        return max(0, self.server.slots - self.server.active
                   - self._admit.qsize())

    def weight_version(self) -> int:
        return int(getattr(self.server, "params_version", 0))

    def prefix_fingerprint(self) -> bytes:
        fn = getattr(self.server, "prefix_fingerprint", None)
        return fn() if fn is not None else b""

    def publish_version(self, store: dict, version: int) -> None:
        """Hold a weight version in the bounded store (newest-kept LRU);
        auto-advancing servers also queue the swap.  A version at or
        below the rollback pin is held but never auto-served."""
        with self._lock:
            self._versions[int(version)] = store
            while len(self._versions) > self._versions_kept:
                # LRU, but NEVER the rollback pin: a pinned fleet keeps
                # receiving newer publications, and evicting the pinned
                # version would strand later rollback retries and
                # scale-out joins at "version not held"
                for held in self._versions:
                    if held != self._pinned:
                        del self._versions[held]
                        break
                else:
                    break
            advance = (self.auto_advance and
                       (self._pinned < 0 or version <= self._pinned))
        if advance:
            self._commands.put(("swap", int(version), None))
            self._wake.set()

    # --------------------------------------------------------- gRPC: submit
    def SubmitStream(self, request: fmsg.DecodeRequest, context):
        """Admit one stream: queue it for the decode loop, then relay its
        chunks.  Rejections (draining, bad request) are an error chunk,
        never a transport failure — the router relays them verbatim."""
        if self._draining or self._stopped.is_set():
            self._obs_errors.add()
            yield fmsg.DecodeChunk(error="server draining", done=True)
            return
        tokens = [int(t) for t in request.tokens]
        if not tokens:
            self._obs_errors.add()
            yield fmsg.DecodeChunk(error="empty prompt", done=True)
            return
        stream = _Stream(
            tokens, int(request.max_new) or 64,
            None if request.temperature < 0 else float(request.temperature),
            [int(t) for t in request.stop])
        self._admit.put(stream)
        self._obs_queue.set(self._admit.qsize())
        self._wake.set()
        try:
            while True:
                try:
                    chunk = stream.out.get(timeout=30.0)
                except queue.Empty:
                    # a wedged decode loop must not hold the client
                    # forever
                    self._obs_errors.add()
                    yield fmsg.DecodeChunk(error="decode stalled",
                                           done=True)
                    return
                if chunk is None:
                    return
                yield chunk
        finally:
            # handler exit for ANY reason the stream did not finish —
            # client disconnect (gRPC closes the generator), the stall
            # timeout above — marks the stream abandoned so the decode
            # loop drops it instead of decoding into a dead queue
            stream.cancelled = True
            self._wake.set()

    # -------------------------------------------------------- gRPC: control
    def Control(self, request: fmsg.DecodeControlRequest,
                context) -> fmsg.DecodeControlResponse:
        action = int(request.action)
        ok, message = True, "ok"
        if action == fmsg.CTRL_SWAP or action == fmsg.CTRL_ROLLBACK:
            version = int(request.version)
            with self._lock:
                if version == -1 and self._versions:
                    version = next(reversed(self._versions))
                held = version in self._versions
                newer_than_pin = (self._pinned >= 0
                                  and version > self._pinned)
                if held and action == fmsg.CTRL_ROLLBACK:
                    # pin FIRST, under the same lock hold that validated
                    # the version: no auto-advance can interleave
                    self._pinned = version
                    newer_than_pin = False
            if not held:
                ok, message = False, f"version {version} not held"
            elif newer_than_pin:
                ok = False
                message = (f"version {version} newer than rollback pin "
                           f"{self._pinned} (Control UNPIN first)")
            else:
                ok, why = self._run_command(("swap", version))
                message = (f"serving version {version}" if ok
                           else f"swap to {version} failed: {why}")
                if ok and action == fmsg.CTRL_ROLLBACK:
                    flight.record("fleet.rollout", a=version,
                                  b=self.server_id, note="rollback-pin")
        elif action == fmsg.CTRL_UNPIN:
            with self._lock:
                self._pinned = -1
            message = "unpinned"
        elif action == fmsg.CTRL_DRAIN:
            self.drain()
            message = "draining"
        elif action != fmsg.CTRL_STATUS:
            ok, message = False, f"unknown control action {action}"
        with self._lock:
            held = list(self._versions)
            pinned = self._pinned
        state = (emsg.MEMBER_DRAINING if self._draining
                 else emsg.MEMBER_ACTIVE)
        return fmsg.DecodeControlResponse(
            success=ok, message=message, server_id=self.server_id,
            state=state, slots=self.server.slots,
            free_slots=self.free_slots(), queue_depth=self.queue_depth(),
            weight_version=self.weight_version(), pinned_version=pinned,
            versions_held=held, streams_served=self.streams_served,
            prefill_tokens=int(getattr(self.server,
                                       "_prefill_tokens", 0)),
            prompt_tokens=int(getattr(self.server, "_prompt_tokens", 0)))

    def _run_command(self, command: tuple,
                     timeout: float = 30.0) -> tuple[bool, str]:
        """Queue a command for the decode loop, wait for it to apply
        (swaps must land at a round boundary — the loop is the only
        thread that may touch the DecodeServer), and return its real
        OUTCOME: "processed" is not "succeeded", and a Control caller
        reporting success for a swap that raised would silently break
        the rollback guarantee."""
        box = _CommandBox()
        self._commands.put((command[0], command[1], box))
        self._wake.set()
        if not box.done.wait(timeout):
            return False, "decode loop busy"
        return box.ok, box.why

    # ----------------------------------------------------------- decode loop
    def _apply_commands(self) -> None:
        """Round-boundary command point: weight swaps requested by
        Control/auto-advance apply here, where no decode round is in
        flight.  The outcome (applied / already current / failed and
        why) flows back to the Control waiter through its box."""
        while True:
            try:
                kind, version, box = self._commands.get_nowait()
            except queue.Empty:
                return
            if kind == "swap":
                with self._lock:
                    store = self._versions.get(version)
                if store is None:
                    # evicted between the Control-side held-check and
                    # here (bounded store under continued publication)
                    box_fail(box, f"version {version} no longer held")
                elif version == self.weight_version():
                    box_ok(box)  # already serving it
                else:
                    try:
                        fresh = (self._transform(store) if self._transform
                                 else store)
                        self.server.swap_params(fresh, version=version)
                        self._obs_swaps.add()
                        flight.record("fleet.swap", a=version,
                                      b=self.server_id)
                        box_ok(box)
                    except Exception as exc:  # noqa: BLE001 — serving
                        # boundary: a bad publication keeps the last-good
                        # weights (PR 10 discipline), never kills decode
                        log.warning("swap to version %d failed (%s); "
                                    "keeping last-good", version, exc)
                        box_fail(box, str(exc))
            elif box is not None:
                box_fail(box, f"unknown command {kind!r}")

    def _poll_feed(self) -> None:
        if self._follower is None:
            return
        fresh = self._follower.poll()
        if fresh is not None:
            self.publish_version(*fresh)

    def _admit_locked_rounds(self) -> None:
        """Admit queued streams into free slots — between rounds, per
        round, no batch barrier.  A submit() rejection becomes that
        stream's error chunk."""
        while self.server.has_free_slot:
            try:
                stream = self._admit.get_nowait()
            except queue.Empty:
                break
            if stream.cancelled:
                continue  # client already gone: never admit it
            try:
                with _DISPATCH_LOCK:
                    rid = self.server.submit(
                        stream.tokens, stream.max_new,
                        temperature=stream.temperature, stop=stream.stop)
            except Exception as exc:  # noqa: BLE001 — per-request error
                # boundary, exactly cli/serve_main.py admit(): malformed
                # requests must never kill in-flight streams
                self._obs_errors.add()
                stream.out.put(fmsg.DecodeChunk(error=str(exc), done=True))
                stream.out.put(None)
                continue
            stream.request_id = rid
            version = self.weight_version()
            if rid in self.server.finished():
                # max_new=1 / instant EOS: completed inside submit()
                for token in self.server.result(rid):
                    stream.out.put(fmsg.DecodeChunk(
                        request_id=rid, token=int(token),
                        weight_version=version))
                stream.out.put(fmsg.DecodeChunk(request_id=rid, done=True,
                                                weight_version=version))
                stream.out.put(None)
                self.streams_served += 1
                self._obs_streams.add()
                continue
            # the prefill already produced the first token
            stream.out.put(fmsg.DecodeChunk(
                request_id=rid, token=int(self.server.peek(rid)[0]),
                weight_version=version))
            self._live[rid] = stream
        self._obs_queue.set(self._admit.qsize())

    def _reap_cancelled(self) -> None:
        """Free the slots of in-flight streams whose client vanished
        (the handler's finally marked them) — an abandoned request must
        not decode its remaining budget into a dead queue."""
        for rid, stream in list(self._live.items()):
            if stream.cancelled:
                del self._live[rid]
                self.server.cancel(rid)

    def _decode_loop(self) -> None:
        while not self._stopped.is_set():
            self._poll_feed()
            self._apply_commands()
            self._reap_cancelled()
            self._admit_locked_rounds()
            if self.server.idle:
                if self._draining and self._admit.qsize() == 0:
                    self._finish_drain()
                    return
                self._wake.wait(timeout=self._heartbeat_s)
                self._wake.clear()
                continue
            with _DISPATCH_LOCK:
                emitted = self.server.step()
            if self._round_delay_s:
                time.sleep(self._round_delay_s)
            version = self.weight_version()
            for rid, token in emitted:
                stream = self._live.get(rid)
                if stream is not None:
                    stream.out.put(fmsg.DecodeChunk(
                        request_id=rid, token=int(token),
                        weight_version=version))
            for rid in set(self.server.finished()) & set(self._live):
                stream = self._live.pop(rid)
                self.server.result(rid)  # tokens already streamed
                stream.out.put(fmsg.DecodeChunk(request_id=rid, done=True,
                                                weight_version=version))
                stream.out.put(None)
                self.streams_served += 1
                self._obs_streams.add()

    def _finish_drain(self) -> None:
        """Drain completed: every in-flight stream finished.  Leave the
        fleet (the registry narrows NOW) and unblock wait_drained()."""
        if self._client is not None and self._registered:
            try:
                self._client.call("UpdateFleet", fmsg.FleetRequest(
                    server_id=self.server_id, action=fmsg.FLEET_LEAVE),
                    timeout=5.0)
            except grpc.RpcError:
                pass  # coordinator gone: nothing left to tell
            self._registered = False
        log.info("decode server %d drained (%d streams served)",
                 self.server_id, self.streams_served)
        self._left.set()

    # ------------------------------------------------------ membership loop
    def _membership_loop(self) -> None:
        try:
            self._client.call("UpdateFleet", fmsg.FleetRequest(
                server_id=self.server_id, action=fmsg.FLEET_REGISTER,
                address=self.address, slots=self.server.slots),
                timeout=5.0)
            self._registered = True
        except grpc.RpcError as exc:
            if _status_code(exc) == grpc.StatusCode.UNIMPLEMENTED:
                log.info("coordinator does not speak UpdateFleet; "
                         "serving standalone")
                self.auto_advance = True  # no controller will ever swap us
                return
        while not self._stopped.is_set() and not self._left.is_set():
            try:
                resp = self._client.call("UpdateFleet", fmsg.FleetRequest(
                    server_id=self.server_id, action=fmsg.FLEET_HEARTBEAT,
                    free_slots=self.free_slots(),
                    queue_depth=self.queue_depth(),
                    weight_version=self.weight_version(),
                    active_streams=len(self._live),
                    # radix prefix-cache fingerprint (ISSUE 20): an
                    # immutable snapshot the decode thread swaps in, so
                    # this cross-thread read needs no lock; empty when
                    # the cache is off (router overlap term degrades
                    # to zero)
                    prefix_fp=self.prefix_fingerprint()), timeout=5.0)
                if not resp.success:
                    # fell out of the table (reap after a stall):
                    # re-register — the row is the router's only view
                    self._client.call("UpdateFleet", fmsg.FleetRequest(
                        server_id=self.server_id,
                        action=fmsg.FLEET_REGISTER, address=self.address,
                        slots=self.server.slots), timeout=5.0)
                    self._registered = True
                elif (int(resp.self_state) == emsg.MEMBER_DRAINING
                        and not self._draining):
                    log.warning("decode server %d: coordinator drain",
                                self.server_id)
                    self._draining = True
                    self._wake.set()
            except grpc.RpcError:
                pass  # transient: keep serving, next beat retries
            if self._stopped.wait(self._heartbeat_s):
                return
