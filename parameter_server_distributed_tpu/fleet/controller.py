"""Coordinator-side fleet controller: autoscaling + rolling updates.

Runs next to :class:`~..core.coordinator_core.CoordinatorCore` (it holds
the core directly — no RPC to itself) and manages the decode fleet
through two mechanisms:

- **Autoscaling** — :func:`scale_decision` is the pure policy: scale out
  one server when fleet-wide slot occupancy (busy slots / total slots,
  admission queues counted as busy demand) sits above the high
  watermark, scale in one when below the low watermark, clamped to
  [min, max]; a manual target (``pst-ctl scale <n>``) overrides the
  watermarks entirely until reset to 0.  The loop acts through a
  ``spawner`` (spawn one decode process / stop a drained one) so the
  same controller drives subprocess fleets and in-process test fleets.
  **Scale-in is drain-before-stop**: the victim is marked DRAINING in
  the fleet table (the PR 13 path), it finishes its in-flight streams
  and LEAVES, and only a GONE server is handed to ``spawner.stop`` —
  a scale-in can never drop a stream.

- **Rolling update / rollback** — :meth:`FleetController.rolling_update`
  walks the ACTIVE servers one at a time and ``Control(SWAP)``s each to
  the target version, confirming the swap before touching the next
  server (streams stay pinned to their server throughout — PR 10
  swap-under-stream semantics make the rollout invisible to them);
  :meth:`rollback` pins every server back to a held version, after
  which no server may serve a newer-version continuation until
  unpinned.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import threading

import grpc

from ..obs import flight
from ..rpc.service import RpcClient
from . import messages as fmsg

log = logging.getLogger("pst.fleet.controller")


@dataclasses.dataclass
class ScalePolicy:
    """Watermark knobs (fractions of total slots occupied)."""
    low: float = 0.3
    high: float = 0.8
    min_servers: int = 1
    max_servers: int = 8


def occupancy(entries) -> float:
    """Fleet-wide demand fraction: (busy slots + queued admissions) over
    total slots across non-GONE, non-DRAINING servers.  Queued requests
    count — a fleet with full queues and full slots is at 1.0+, which is
    exactly the scale-out signal."""
    live = [e for e in entries
            if int(e.state) == fmsg.MEMBER_ACTIVE]
    total = sum(int(e.slots) for e in live)
    if total <= 0:
        return 0.0
    busy = sum(int(e.slots) - int(e.free_slots) for e in live)
    queued = sum(int(e.queue_depth) for e in live)
    return (busy + queued) / total


def scale_decision(entries, policy: ScalePolicy,
                   manual_target: int = 0) -> int:
    """Desired fleet size given the current table.  Manual target wins;
    otherwise one step in the watermark's direction (never a jump — each
    new server changes the occupancy the next decision sees)."""
    current = sum(1 for e in entries
                  if int(e.state) in (fmsg.MEMBER_ACTIVE,
                                      fmsg.MEMBER_JOINING))
    if manual_target > 0:
        return max(policy.min_servers,
                   min(policy.max_servers, manual_target))
    occ = occupancy(entries)
    if occ > policy.high and current < policy.max_servers:
        return current + 1
    if occ < policy.low and current > policy.min_servers:
        return current - 1
    return max(policy.min_servers, min(policy.max_servers, current))


class FleetController:
    """See module docstring.  ``spawner`` implements ``spawn() -> None``
    (launch one decode process that will register itself) and
    ``stop(server_id) -> None`` (reap a GONE process)."""

    def __init__(self, core, *, policy: ScalePolicy | None = None,
                 spawner=None, interval_s: float = 0.5):
        self.core = core
        self.policy = policy or ScalePolicy()
        self.spawner = spawner
        self._interval = float(interval_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # server ids this controller marked DRAINING and still owes a
        # spawner.stop once they reach GONE (decode loop thread only)
        self._stopping: set[int] = set()
        self._clients: dict[str, RpcClient] = {}

    # ------------------------------------------------------------- clients
    def _control(self, address: str, action: int,
                 version: int = -1,
                 timeout: float = 30.0) -> fmsg.DecodeControlResponse:
        client = self._clients.get(address)
        if client is None:
            client = RpcClient(address, fmsg.DECODE_SERVICE,
                               fmsg.DECODE_METHODS)
            self._clients[address] = client
        return client.call(
            "Control",
            fmsg.DecodeControlRequest(action=action, version=version),
            timeout=timeout)

    def close(self) -> None:
        self.stop_autoscaler()
        clients, self._clients = dict(self._clients), {}
        for client in clients.values():
            client.close()

    # ------------------------------------------------------ rolling update
    def _active_servers(self):
        _epoch, entries, _target = self.core.fleet_table()
        return [e for e in entries if e.state == fmsg.MEMBER_ACTIVE]

    def rolling_update(self, version: int = -1,
                       timeout: float = 30.0) -> dict[int, bool]:
        """Swap every ACTIVE server to ``version`` (-1 = each server's
        newest held), ONE SERVER AT A TIME — a swap must confirm before
        the next server is touched, so at most one server is mid-swap at
        any moment and every pinned stream keeps flowing (the swap
        itself lands between decode rounds).  Returns {server_id: ok}."""
        results: dict[int, bool] = {}
        for member in self._active_servers():
            flight.record("fleet.rollout", a=version,
                          b=member.server_id, note="swap")
            try:
                resp = self._control(member.address, fmsg.CTRL_SWAP,
                                     version, timeout=timeout)
                results[member.server_id] = bool(resp.success)
                if not resp.success:
                    log.warning("rollout: server %d refused version %d "
                                "(%s)", member.server_id, version,
                                resp.message)
            except grpc.RpcError as exc:
                log.warning("rollout: server %d unreachable (%s)",
                            member.server_id, exc)
                results[member.server_id] = False
        return results

    def rollback(self, version: int,
                 timeout: float = 30.0) -> dict[int, bool]:
        """Pin the whole fleet back to ``version``: each server swaps to
        it AND refuses anything newer until unpinned — after this
        returns, no continuation anywhere in the fleet decodes under a
        newer version."""
        results: dict[int, bool] = {}
        for member in self._active_servers():
            flight.record("fleet.rollout", a=version,
                          b=member.server_id, note="rollback")
            try:
                resp = self._control(member.address, fmsg.CTRL_ROLLBACK,
                                     version, timeout=timeout)
                results[member.server_id] = bool(resp.success)
            except grpc.RpcError:
                results[member.server_id] = False
        return results

    def unpin(self) -> None:
        for member in self._active_servers():
            try:
                self._control(member.address, fmsg.CTRL_UNPIN)
            except grpc.RpcError:
                pass  # unreachable server re-pins nothing

    # ---------------------------------------------------------- autoscaler
    def scale_step(self) -> int:
        """One autoscale decision + action.  Returns the desired size.
        Scale-out spawns immediately; scale-in DRAINS the youngest
        ACTIVE server and stops it only after the fleet table shows it
        GONE (drain-before-stop — the in-flight streams finish first)."""
        _epoch, entries, manual = self.core.fleet_table()
        # finish any pending drain-stops first: a drained server has
        # left the table (GONE) and can now be reaped
        for entry in entries:
            if (entry.server_id in self._stopping
                    and entry.state == fmsg.MEMBER_GONE):
                self._stopping.discard(entry.server_id)
                if self.spawner is not None:
                    self.spawner.stop(entry.server_id)
        desired = scale_decision(entries, self.policy, manual)
        current = [e for e in entries if e.state == fmsg.MEMBER_ACTIVE]
        draining = sum(1 for e in entries
                       if e.state == fmsg.MEMBER_DRAINING)
        if desired > len(current) + draining and self.spawner is not None:
            flight.record("fleet.scale", a=desired, b=len(current),
                          note="scale-out")
            log.info("fleet scale-out: %d -> %d", len(current), desired)
            self.spawner.spawn()
        elif desired < len(current) and draining == 0:
            # one drain in flight at a time: the next decision sees the
            # narrowed fleet and re-evaluates before picking another
            # victim.  Youngest first — the longest-lived server has the
            # warmest caches and the most history.
            victim = max(current, key=lambda e: e.server_id)
            flight.record("fleet.scale", a=desired,
                          b=victim.server_id, note="scale-in-drain")
            log.info("fleet scale-in: draining server %d",
                     victim.server_id)
            self.core.fleet_drain(victim.server_id)
            self._stopping.add(victim.server_id)
        return desired

    def start_autoscaler(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._autoscale_loop,
                                        daemon=True,
                                        name="fleet-autoscaler")
        self._thread.start()

    def stop_autoscaler(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)

    def _autoscale_loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.scale_step()
            except Exception:  # noqa: BLE001 — the autoscaler must keep
                # ticking through a transient RPC/spawn failure; the next
                # interval retries with a fresh table
                log.exception("autoscale step failed")


def expected_servers(streams_per_s: float, tokens_per_stream: float,
                     tokens_per_s_per_slot: float, slots: int) -> int:
    """Little's-law sizing helper for operators: the fleet size at which
    offered load occupies ~70%% of slots."""
    if tokens_per_s_per_slot <= 0 or slots <= 0:
        return 1
    demand_slots = (streams_per_s * tokens_per_stream
                    / tokens_per_s_per_slot)
    return max(1, math.ceil(demand_slots / (0.7 * slots)))
