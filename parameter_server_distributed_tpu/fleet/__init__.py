"""Decode fleet control plane (ISSUE 14).

The serving analogue of what elastic/ (PR 13) and replication/ (PR 7)
built for training: DecodeServers register with the coordinator over the
``UpdateFleet`` extension RPC, a front-door :class:`~.router.FleetRouter`
(``pst-route``) admits and load-balances token streams across them by
free-slot/queue-depth score (pinning each stream to its server for its
lifetime), and a :class:`~.controller.FleetController` scales decode
processes out/in under slot-occupancy watermarks (scale-in drains before
stopping — the PR 13 DRAINING path) and drives rolling weight updates /
rollbacks across the fleet with streams pinned mid-rollout.

Downgrade matrix: without a router, single-server ``pst-serve`` is
byte-unchanged; against a reference coordinator (no ``UpdateFleet``),
registration degrades to standalone serving.
"""

from . import messages  # noqa: F401
