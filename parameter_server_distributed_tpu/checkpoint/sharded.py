"""Sharded checkpointing for the SPMD data plane (orbax-backed).

The host-side manager (manager.py) serializes the PS's host store in the
reference's binary format.  The SPMD path's TrainState is a pytree of
*sharded* jax Arrays — saving it through the host codec would gather every
shard to one host.  Orbax writes each shard from the device that owns it
and restores into any mesh/sharding, which is also what makes elastic
resharding cheap (SURVEY.md §7 "hard parts": checkpoint-restore into the
new mesh).

Layout per step: ``<dir>/step_<N>/`` (orbax tree) and the same epoch-style
naming contract as the host manager for discovery.
"""

from __future__ import annotations

import os
import re
from typing import Any

import jax
import numpy as np

_STEP_RE = re.compile(r"step_(\d+)$")


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


_async_ckptr = None


def _async_checkpointer():
    global _async_ckptr
    if _async_ckptr is None:
        import orbax.checkpoint as ocp
        _async_ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
    return _async_ckptr


def save_sharded(directory: str, step: int, state: Any,
                 asynchronous: bool = False) -> str:
    """Save a (possibly sharded) pytree; returns the checkpoint path.

    ``asynchronous=True`` returns as soon as device buffers are snapshotted
    and writes in a background thread (orbax AsyncCheckpointer) — the train
    loop keeps stepping while the filesystem write happens.  Call
    :func:`wait_for_saves` before reading the checkpoint or exiting.
    Incomplete async writes live under a tmp-suffixed dirname, so
    :func:`latest_step` never discovers a partial checkpoint."""
    path = os.path.join(os.path.abspath(directory), f"step_{step}")
    if asynchronous:
        _async_checkpointer().save(path, state, force=True)
    else:
        _checkpointer().save(path, state, force=True)
    return path


def wait_for_saves() -> None:
    """Block until all pending asynchronous saves have committed."""
    if _async_ckptr is not None:
        _async_ckptr.wait_until_finished()


def restore_sharded(path: str, template: Any | None = None) -> Any:
    """Restore a pytree.  With ``template`` (a pytree of sharded arrays or
    jax.ShapeDtypeStruct with shardings), shards land directly on their
    owning devices — pass the target TrainState to reshard on restore.
    Without a template, leaves come back as HOST numpy arrays: the
    checkpoint may have been written by a mesh this process doesn't have
    (e.g. pst-generate reading a pst-train checkpoint on one chip), so no
    device placement is assumed."""
    import orbax.checkpoint as ocp

    checkpointer = _checkpointer()
    if template is None:
        meta = checkpointer.metadata(path).item_metadata
        restore_args = jax.tree.map(
            lambda _: ocp.RestoreArgs(restore_type=np.ndarray), meta.tree)
        return checkpointer.restore(path, restore_args=restore_args)

    def as_restore_type(leaf):
        if isinstance(leaf, jax.Array) and hasattr(leaf, "sharding"):
            return ocp.ArrayRestoreArgs(sharding=leaf.sharding,
                                        global_shape=leaf.shape)
        return ocp.RestoreArgs()

    restore_args = jax.tree.map(as_restore_type, template)
    return checkpointer.restore(path, item=template,
                                restore_args=restore_args)


def restore_latest(directory: str, template: Any | None = None):
    """Restore the newest ``step_N`` checkpoint under ``directory``;
    returns (step, state) or (None, None) when none exists.  The single
    discovery+restore path shared by the train loop's --resume and the
    generation CLI."""
    step = latest_step(directory)
    if step is None:
        return None, None
    path = os.path.join(os.path.abspath(directory), f"step_{step}")
    return step, restore_sharded(path, template=template)


def average_checkpoints(directory: str, last_k: int,
                        template: Any | None = None):
    """Uniform parameter average of the newest ``last_k`` committed
    checkpoints (classic checkpoint averaging / poor-man's EMA: smooths
    the SGD noise of the final steps, often worth a few eval points).
    Params accumulate in float32 and cast back to the stored dtype; the
    non-param parts (optimizer state, step) come from the NEWEST
    checkpoint.  Returns (newest_step, averaged_state) or (None, None)
    when the directory has no checkpoints; when fewer than ``last_k``
    exist, the available ones are averaged (loudly).

    Older checkpoints are restored one at a time and dropped right after
    their params are accumulated, so peak memory is one full state plus
    the f32 accumulator (a params-only partial restore would shave the
    transient optimizer-state read; not worth the orbax plumbing at
    these sizes)."""
    import logging

    import jax.numpy as jnp

    steps = _committed_steps(directory)[:max(1, last_k)]
    if not steps:
        return None, None
    if len(steps) < last_k:
        logging.getLogger("pst.checkpoint").warning(
            "average_checkpoints: asked for last %d but only %d committed "
            "checkpoints exist — averaging %d", last_k, len(steps),
            len(steps))
    base = os.path.abspath(directory)
    newest = restore_sharded(os.path.join(base, f"step_{steps[0]}"),
                             template=template)
    params = (newest["params"] if isinstance(newest, dict)
              else newest.params)
    acc = jax.tree.map(lambda p: p.astype(jnp.float32), params)
    for step in steps[1:]:
        other = restore_sharded(os.path.join(base, f"step_{step}"),
                                template=template)
        op = other["params"] if isinstance(other, dict) else other.params
        acc = jax.tree.map(lambda a, p: a + p.astype(jnp.float32), acc, op)
        del other, op
    avg = jax.tree.map(
        lambda a, p: (a / len(steps)).astype(p.dtype), acc, params)
    if isinstance(newest, dict):
        newest = dict(newest, params=avg)
    else:
        import dataclasses as _dc
        newest = _dc.replace(newest, params=avg)
    return steps[0], newest


def _committed_steps(directory: str) -> list[int]:
    """Step numbers of COMMITTED step_N checkpoints (in-flight async writes
    live under tmp-suffixed names the regex rejects), newest first.  The
    single discovery scan shared by latest_step and prune_checkpoints."""
    if not os.path.isdir(directory):
        return []
    return sorted(
        (int(match.group(1)) for name in os.listdir(directory)
         if (match := _STEP_RE.search(name))), reverse=True)


def latest_step(directory: str) -> int | None:
    steps = _committed_steps(directory)
    return steps[0] if steps else None


def prune_checkpoints(directory: str, keep: int) -> list[int]:
    """Delete all but the newest ``keep`` committed step_N checkpoints
    (the sharded analogue of the host manager's retention —
    checkpoint/manager.py).  Multi-controller runs must call this from
    ONE process (the train loop gates on process_index() == 0 — orbax
    saves are coordinated, deletion must be too).  Returns the deleted
    step numbers; failures are logged, not swallowed."""
    import logging
    import shutil

    if keep <= 0:
        return []
    deleted = []
    for step in _committed_steps(directory)[keep:]:
        path = os.path.join(directory, f"step_{step}")
        try:
            shutil.rmtree(path)
            deleted.append(step)
        except OSError as exc:
            logging.getLogger("pst.checkpoint").warning(
                "retention could not delete %s: %s", path, exc)
    return deleted
