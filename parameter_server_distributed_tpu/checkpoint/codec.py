"""Checkpoint binary codec, format-compatible with the reference.

The reference writes a custom little-endian binary layout from
`ParameterServerCore::save_checkpoint` (reference: src/parameter_server.cpp:112-144)
and reads it back in `load_checkpoint` (:146-188):

    epoch            int32
    current_iteration int32
    num_tensors      size_t (8 bytes on the reference's x86-64 targets)
    per tensor:
      name_len  size_t | name bytes
      shape_len size_t | shape int32[shape_len]
      dtype     int32
      data_len  size_t | data float32[data_len]

This module reproduces that layout byte-for-byte (a checkpoint written by
the reference loads here and vice versa) and adds integrity-preserving
atomic writes (tmp file + rename — the reference writes in place).  The
bulk float I/O is numpy tobytes/frombuffer, i.e. already memcpy-speed; no
native path is needed.
"""

from __future__ import annotations

import os
import struct
from typing import Mapping

import numpy as np

from ..core.tensor import TensorStore

_I32 = struct.Struct("<i")
_U64 = struct.Struct("<Q")


def dumps(epoch: int, iteration: int, params: Mapping[str, np.ndarray]) -> bytes:
    # Device-resident stores (PSDT_DEVICE_APPLY, ISSUE 11): start every
    # tensor's D2H copy before the serial np.asarray sweep below, so the
    # transfers overlap instead of serializing one tensor at a time.
    # The on-disk bytes are identical either way — np.asarray of a jax
    # f32 Array yields the same f32 host bytes the numpy store holds.
    from ..core.device_apply import readback_async

    readback_async(params)
    out = bytearray()
    out += _I32.pack(int(epoch))
    out += _I32.pack(int(iteration))
    out += _U64.pack(len(params))
    for name, arr in params.items():
        arr = np.asarray(arr, dtype="<f4")
        name_b = name.encode("utf-8")
        out += _U64.pack(len(name_b))
        out += name_b
        shape = arr.shape
        out += _U64.pack(len(shape))
        for dim in shape:
            out += _I32.pack(int(dim))
        out += _I32.pack(0)  # dtype: 0 = float32 (only dtype the format carries)
        flat = arr.reshape(-1)
        out += _U64.pack(flat.size)
        out += flat.tobytes()
    return bytes(out)


def loads(buf: bytes) -> tuple[int, int, TensorStore]:
    """Returns (epoch, iteration, params)."""
    pos = 0

    def take(n: int) -> bytes:
        nonlocal pos
        if pos + n > len(buf):
            raise ValueError(f"truncated checkpoint at offset {pos} (+{n})")
        chunk = buf[pos:pos + n]
        pos += n
        return chunk

    epoch = _I32.unpack(take(4))[0]
    iteration = _I32.unpack(take(4))[0]
    num_tensors = _U64.unpack(take(8))[0]
    if num_tensors > 1 << 32:
        raise ValueError(f"implausible tensor count {num_tensors}")
    params: TensorStore = {}
    for _ in range(num_tensors):
        name_len = _U64.unpack(take(8))[0]
        name = take(name_len).decode("utf-8")
        shape_len = _U64.unpack(take(8))[0]
        shape = [_I32.unpack(take(4))[0] for _ in range(shape_len)]
        dtype = _I32.unpack(take(4))[0]
        if dtype not in (0, 1):
            raise ValueError(f"unknown dtype {dtype} for tensor {name!r}")
        data_len = _U64.unpack(take(8))[0]
        itemsize = 4 if dtype == 0 else 8
        raw = take(data_len * itemsize)
        arr = np.frombuffer(raw, dtype="<f4" if dtype == 0 else "<f8").astype(np.float32)
        params[name] = arr.reshape(shape) if shape else arr
    return epoch, iteration, params


def save(path: str, epoch: int, iteration: int,
         params: Mapping[str, np.ndarray]) -> None:
    """Atomic save: write to a tmp file in the same directory, fsync, rename.
    (The reference writes in place — a crash mid-write corrupts the file.)"""
    data = dumps(epoch, iteration, params)
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load(path: str) -> tuple[int, int, TensorStore]:
    with open(path, "rb") as f:
        return loads(f.read())
