"""Checkpoint manager: periodic epoch-advance autosave + on-demand save/load.

Reproduces the reference's checkpoint daemon semantics
(reference: src/parameter_server_service.cpp:150-169): every
``check_period_s`` (5 s) compute ``epoch = current_iteration //
checkpoint_interval``; when the epoch advances past the last saved epoch,
write ``checkpoint_epoch_<N>.ckpt`` (same filename convention).  Adds what
the reference lacks: atomic writes (codec.save), retention of the newest K
files, optimizer-state sidecars, and a clean stop.
"""

from __future__ import annotations

import glob
import json
import os
import re
import threading
from typing import Callable

import numpy as np

from ..analysis.lock_order import checked_lock
from ..core.ps_core import ParameterServerCore
from . import codec

_CKPT_RE = re.compile(r"checkpoint_epoch_(\d+)\.ckpt$")


def checkpoint_filename(epoch: int) -> str:
    """reference: src/parameter_server_service.cpp:160."""
    return f"checkpoint_epoch_{epoch}.ckpt"


class CheckpointManager:
    def __init__(self,
                 core: ParameterServerCore,
                 directory: str = ".",
                 checkpoint_interval: int = 10,
                 check_period_s: float = 5.0,
                 keep: int = 0,
                 on_save: Callable[[str, int], None] | None = None):
        self._core = core
        self._dir = directory
        self._interval = max(1, int(checkpoint_interval))
        self._period = check_period_s
        self._keep = int(keep)
        self._on_save = on_save
        self._last_saved_epoch = -1
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # RLock: save() locks itself AND is called by maybe_autosave() under
        # the same lock — an on-demand SaveCheckpoint RPC racing the autosave
        # daemon must not interleave writes on the same .tmp file.  Held
        # across core.snapshot()/restore(), so it ranks BEFORE every core
        # lock (analysis/lock_order.py; order-asserted under
        # PSDT_LOCK_CHECK=1).
        self._lock = checked_lock("CheckpointManager._lock", reentrant=True)

    # ----------------------------------------------------------- daemon
    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="checkpoint-autosave")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _loop(self) -> None:
        while not self._stop.wait(self._period):
            self.maybe_autosave()

    def maybe_autosave(self) -> str | None:
        """Epoch-advance check (reference: parameter_server_service.cpp:153-168).
        Returns the path written, or None."""
        epoch = self._core.current_iteration // self._interval
        with self._lock:
            if epoch <= self._last_saved_epoch:
                return None
            if not self._core.get_parameters():
                # nothing to save yet: don't burn the epoch slot on an empty
                # checkpoint (restoring one would wipe live parameters)
                return None
            return self.save(epoch=epoch)

    # ------------------------------------------------------------ save/load
    def save(self, epoch: int | None = None, path: str | None = None) -> str:
        """On-demand save (reference RPC SaveCheckpoint —
        src/parameter_server_service.cpp:97-115; path defaults to the
        epoch-filename convention)."""
        with self._lock:
            snap_epoch, iteration, params = self._core.snapshot()
            epoch = snap_epoch if epoch is None else int(epoch)
            if path is None:
                path = os.path.join(self._dir, checkpoint_filename(epoch))
            codec.save(path, epoch, iteration, params)
            opt_state = self._core.optimizer_state()
            if opt_state:
                _save_optimizer_sidecar(path, opt_state)
            # store-version meta sidecar (delta serving, ISSUE 10): the
            # version counter at save time, so a LATER process restoring
            # this file resumes numbering past it and a version id the
            # saving process already served can never name different
            # values.  Read after snapshot — a concurrent bump makes the
            # recorded version only larger, which is the safe direction.
            _save_meta_sidecar(path, {
                "params_version": int(self._core.params_version)})
            self._core.epoch = epoch
            self._last_saved_epoch = max(self._last_saved_epoch, epoch)
            self._apply_retention()
        if self._on_save is not None:
            self._on_save(path, epoch)
        return path

    def load(self, path: str) -> tuple[int, int]:
        """Restore PS state from a checkpoint file (reference RPC
        LoadCheckpoint — src/parameter_server_service.cpp:118-148).
        Returns (epoch, iteration)."""
        epoch, iteration, params = codec.load(path)
        if not params:
            raise ValueError(f"refusing to restore empty checkpoint {path!r}")
        opt_state = _load_optimizer_sidecar(path)
        meta = _load_meta_sidecar(path)
        with self._lock:
            self._core.restore(
                epoch, iteration, params, optimizer_state=opt_state,
                # serve_version monotonicity across processes: restore
                # resumes version numbering past the save-time counter
                # (core.restore also bumps past everything THIS process
                # served) — a delta receiver can never be told a version
                # id it holds now names different values (ISSUE 10)
                params_version=int(meta.get("params_version", 0)))
            self._last_saved_epoch = max(self._last_saved_epoch, epoch)
        return epoch, iteration

    def latest(self) -> str | None:
        """Newest checkpoint in the directory by epoch number."""
        best, best_epoch = None, -1
        for path in glob.glob(os.path.join(self._dir, "checkpoint_epoch_*.ckpt")):
            match = _CKPT_RE.search(path)
            if match and int(match.group(1)) > best_epoch:
                best, best_epoch = path, int(match.group(1))
        return best

    def _apply_retention(self) -> None:
        if self._keep <= 0:
            return
        found = []
        for path in glob.glob(os.path.join(self._dir, "checkpoint_epoch_*.ckpt")):
            match = _CKPT_RE.search(path)
            if match:
                found.append((int(match.group(1)), path))
        found.sort()
        for _, path in found[:-self._keep]:
            try:
                os.remove(path)
                for suffix in (".opt.npz", ".meta.json"):
                    sidecar = path + suffix
                    if os.path.exists(sidecar):
                        os.remove(sidecar)
            except OSError:
                pass


def _save_meta_sidecar(path: str, meta: dict) -> None:
    """Framework-only metadata next to the checkpoint (atomic, JSON).
    Deliberately a sidecar: the .ckpt byte layout is pinned to the
    reference (checkpoint/codec.py) and must stay loadable by it."""
    tmp = path + ".meta.json.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(meta, f)
    os.replace(tmp, path + ".meta.json")


def _load_meta_sidecar(path: str) -> dict:
    """Meta sidecar contents, values normalized ({} for reference-written
    checkpoints).  Best-effort by contract: a missing, unparseable, or
    wrong-typed OPTIONAL sidecar must never block restoring a valid
    .ckpt."""
    try:
        with open(path + ".meta.json", encoding="utf-8") as f:
            loaded = json.load(f)
        if not isinstance(loaded, dict):
            return {}
        loaded["params_version"] = int(loaded.get("params_version") or 0)
        return loaded
    except (OSError, ValueError, TypeError):
        return {}


def _save_optimizer_sidecar(path: str, state: dict) -> None:
    """Flatten the optimizer state dict into an npz next to the checkpoint."""
    flat: dict[str, np.ndarray] = {}
    for slot, value in state.items():
        if isinstance(value, dict):
            for name, arr in value.items():
                flat[f"{slot}/{name}"] = np.asarray(arr)
        else:
            flat[f"__scalar__/{slot}"] = np.asarray(value)
    tmp = path + ".opt.npz.tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **flat)
    os.replace(tmp, path + ".opt.npz")


def _load_optimizer_sidecar(path: str) -> dict | None:
    sidecar = path + ".opt.npz"
    if not os.path.exists(sidecar):
        return None
    state: dict = {}
    with np.load(sidecar) as npz:
        for key in npz.files:
            slot, _, name = key.partition("/")
            if slot == "__scalar__":
                value = npz[key]
                state[name] = value.item() if value.ndim == 0 else value
            else:
                state.setdefault(slot, {})[name] = npz[key]
    return state
