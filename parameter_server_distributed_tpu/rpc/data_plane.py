"""Streaming data plane for the parameter-server service.

The reference moves every push/pull as ONE unary protobuf message
(reference proto/parameter_server.proto:5-11).  At config-3 scale (GBs of
tensors per push) a monolithic message serializes encode -> transport ->
decode, peaks at several whole-store-sized buffers, and hits gRPC's
message-size ceiling.  This framework extension moves the same payloads as
a STREAM of chunk messages, each carrying a subset of the tensors:

- ``PushGradientsStream`` (client-streaming): gRPC pulls the request
  iterator from a sender thread, so chunk N+1's fused encode
  (wire.ArrayPayload) overlaps chunk N's transport, and the server's
  per-chunk decode + f32 conversion overlaps receiving later chunks.
- ``ServeParametersStream`` (server-streaming): the server encodes and
  ships tensors chunk by chunk; the client converts each chunk while the
  next is in flight.
- ``PushPullStream`` (bidirectional): the fused synchronous step.  The
  client streams its gradient chunks; the server applies them, parks on
  the aggregation barrier (condition variable — core/ps_core.py
  ``wait_for_aggregation``), and streams the fresh parameter chunks back
  on the same call.  One RPC round replaces push + M× CheckSyncStatus
  polls + pull, and because the request side accepts a LAZY tensor
  iterator, the worker's bucketed D2H fetch ⊕ compress ⊕ encode ⊕
  transport all pipeline per bucket (worker/trainer.py GradientBuckets).

Chunks reuse the wire-compatible ``GradientUpdate`` / ``ParameterUpdate``
schemas (a chunk is just a smaller message), so nothing new exists at the
encoding layer.  Reference peers are unaffected: these are extra method
names on the same gRPC service, and :class:`PSClient` permanently falls
back to the reference's unary RPCs for a connection the first time the
server answers UNIMPLEMENTED — so it interoperates with a reference PS
unchanged.

A single tensor larger than the chunk budget rides alone in one oversized
chunk (tensors are never split mid-payload); the budget is a grouping
target, not a hard message cap.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Iterable, Iterator, Sequence

import grpc

from ..delta.client import (DeltaBaseMismatch, DeltaPullState,
                            DeltaRoundResult, apply_frames)
from ..delta.messages import (DELTA_PS_METHODS, DeltaPullRequest,
                              DeltaPushChunk, delta_enabled)
from ..obs import flight
from ..obs import stats as obs_stats
from ..obs import trace as obs_trace
from . import messages as m
from . import shm_transport
# The wire payload codec (ISSUE 6): every packed tensor payload on this
# data plane encodes/decodes through this narrow interface — PythonCodec
# is the byte-identity oracle and fallback, NativeCodec the zero-copy C++
# fast path selected per process via PSDT_NATIVE (see codec.py).
from .codec import (Codec, NativeCodec, PythonCodec,  # noqa: F401 — public
                    active_codec)
from .service import RpcClient
from .service import status_code as _status_code
from .wire import WT_LEN, WT_VARINT, _len_delimited_size, _tag, _varint_size, \
    _Writer, encode_varint

log = logging.getLogger("pst.data_plane")

# Default chunk budget for streamed pushes/pulls.  Tens of MB amortizes
# per-message overhead while keeping encode/transport/decode pipelined;
# PSDT_STREAM_CHUNK_BYTES overrides, 0 disables streaming entirely.
DEFAULT_CHUNK_BYTES = 32 << 20


def stream_chunk_bytes() -> int:
    return int(os.environ.get("PSDT_STREAM_CHUNK_BYTES",
                              str(DEFAULT_CHUNK_BYTES)))


def bucket_bytes() -> int:
    """Bucket budget for the worker's incremental gradient D2H fetch
    (worker/trainer.py GradientBuckets).  Defaults to the stream chunk
    budget so D2H buckets and wire chunks stay aligned; PSDT_BUCKET_BYTES
    overrides independently (0 falls back to whole-store fetch)."""
    raw = os.environ.get("PSDT_BUCKET_BYTES")
    if raw is not None:
        return int(raw)
    return stream_chunk_bytes()


def decode_gradients(tensors: Iterable[m.Tensor],
                     device: bool = False) -> dict:
    """Decode one push chunk's wire Tensors into fold-ready arrays.

    ``device=False`` (the default, and the only behavior before
    ISSUE 11): host numpy via ``Tensor.to_array`` — byte-identical to
    the pre-existing fold input.  ``device=True`` (the serving core
    asked for device folds — ``ParameterServerCore.device_fold``): each
    packed payload lands as a jax device buffer with the dequantize
    running ON DEVICE (core/device_apply.tensor_to_device — int8 wire
    bytes cross the host boundary at a quarter of the f32 volume, bf16
    at half), so the accumulator sums and the sharded optimizer apply
    never round-trip through host numpy."""
    if device:
        from ..core import device_apply

        return {t.name: device_apply.tensor_to_device(t) for t in tensors}
    return {t.name: t.to_array() for t in tensors}


def _tensor_nbytes(t: m.Tensor) -> int:
    if t.packed:
        return len(t.packed)
    data = t.data
    return getattr(data, "nbytes", 4 * len(data))


def split_tensors(tensors: Iterable[m.Tensor],
                  chunk_bytes: int) -> Iterator[list[m.Tensor]]:
    """Greedy-pack tensors into order-preserving chunks of roughly
    ``chunk_bytes`` payload each.  Cheap: only metadata is touched (the
    payloads are lazy ArrayPayloads or buffer views)."""
    group: list[m.Tensor] = []
    size = 0
    for t in tensors:
        n = _tensor_nbytes(t)
        if group and size + n > chunk_bytes:
            yield group
            group, size = [], 0
        group.append(t)
        size += n
    if group:
        yield group


_PARAMETERS_FIELD = 2  # m.ParameterUpdate.parameters
_ITERATION_FIELD = 1   # m.ParameterUpdate.iteration
_READY_FIELD = 3       # m.ParameterUpdate.ready


def encode_parameter_record_groups(
        groups: Sequence[Sequence[m.Tensor]],
        stripes: int | None = None) -> list[bytes]:
    """Encode several chunk groups' ``ParameterUpdate.parameters`` bodies,
    fanning the per-group :func:`encode_parameter_records` passes across
    the shared stripe executor (core/stripes.py) when more than one group
    and more than one stripe are configured.  ``stripes`` is the serving
    core's resolved stripe count (so a ``ParameterServerCore(stripes=1)``
    serial escape hatch is honored here too, not only via PSDT_STRIPES);
    None falls back to the env/core-count default.  Group order is
    preserved and each group's bytes are exactly what the serial encode
    produces — the wire format is untouched, only WHICH thread runs each
    group's payload casts/packs changes (the numpy casts release the GIL,
    so a multi-chunk store encodes on multiple cores).

    Flat-arena stores (core/arena.py ArenaStore, ISSUE 15) feed this
    fan-out ZERO-COPY by construction: their tensor values are numpy
    views slicing the per-stripe readback slab by packing-table offset,
    so the payload casts/packs here read the slab directly instead of
    re-gathering per-tensor device buffers — and because view identity
    never changes the f32 values, the encoded bytes are byte-identical
    to the per-tensor path's."""
    from ..core.stripes import run_striped, stripe_count

    if len(groups) <= 1 or stripe_count(stripes) <= 1:
        return [encode_parameter_records(group) for group in groups]
    return run_striped([(lambda g=group: encode_parameter_records(g))
                        for group in groups])


def encode_parameter_records(tensors: Iterable[m.Tensor]) -> bytes:
    """Encode a group of wire Tensors ONCE into the exact bytes of
    ``ParameterUpdate.parameters`` (field 2) records — tag, length, and
    tensor body per element.  The server's encode-once broadcast cache
    (server/ps_service.py) stores these and replays them to every puller
    of the same (params version, wire dtype) via
    :class:`PreEncodedParameterUpdate`, so the per-tensor payload encode
    (f32→bf16 cast, repeated-float pack) runs once per version instead of
    once per pulling worker."""
    items = [(t, t.encoded_size()) for t in tensors]
    writer = _Writer(sum(_len_delimited_size(_PARAMETERS_FIELD, size)
                         for _, size in items))
    for tensor, size in items:
        writer.write(_tag(_PARAMETERS_FIELD, WT_LEN))
        writer.write(encode_varint(size))
        tensor.encode_into(writer)
    return writer.getvalue()


class PreEncodedParameterUpdate:
    """A ``ParameterUpdate`` whose ``parameters`` field is pre-encoded wire
    bytes (one or more :func:`encode_parameter_records` blobs).  Encodes
    byte-identically to ``m.ParameterUpdate(...)`` with the same content —
    field order 1, 2, 3 with proto3 default elision — so reference-shaped
    clients decode it indistinguishably.  Quacks like a codec Message
    (``encode`` / ``encoded_size`` / ``encode_into``), which is all the
    gRPC serializer and the ``PushPullResponse.params`` embedding need."""

    __slots__ = ("iteration", "ready", "bodies")

    def __init__(self, iteration: int, ready: bool,
                 bodies: Sequence[bytes]):
        self.iteration = int(iteration)
        self.ready = bool(ready)
        self.bodies = bodies

    def encoded_size(self) -> int:
        size = sum(len(b) for b in self.bodies)
        if self.iteration:
            size += (_varint_size(_ITERATION_FIELD << 3)
                     + _varint_size(self.iteration))
        if self.ready:
            size += _varint_size(_READY_FIELD << 3) + 1
        return size

    def encode_into(self, writer: "_Writer") -> None:
        if self.iteration:
            writer.write(_tag(_ITERATION_FIELD, WT_VARINT))
            writer.write(encode_varint(self.iteration))
        for body in self.bodies:
            writer.write(memoryview(body))
        if self.ready:
            writer.write(_tag(_READY_FIELD, WT_VARINT))
            writer.write(b"\x01")

    def encode(self) -> bytes:
        writer = _Writer(self.encoded_size())
        self.encode_into(writer)
        return writer.getvalue()


class PSClient(RpcClient):
    """Parameter-server client with the streaming data plane.

    ``push_gradients`` / ``pull_parameters`` use the chunk-stream RPCs and
    transparently fall back (once, remembered per connection) to the
    reference unary RPCs when the server does not implement them.  All
    other methods are plain :meth:`RpcClient.call`.
    """

    # single-PS fused topology: the hierarchical-aggregation tier
    # (tiers/group_client.py) can interpose a same-host leaf aggregator
    # in front of this connection; the sharded fan-out client says False
    supports_tiers = True

    def __init__(self, target: str,
                 service: str = m.PARAMETER_SERVER_SERVICE,
                 methods=None, chunk_bytes: int | None = None):
        methods = dict(methods or m.PARAMETER_SERVER_METHODS)
        methods.update(m.PARAMETER_SERVER_STREAM_METHODS)
        methods.update(shm_transport.SHM_METHODS)
        methods.update(DELTA_PS_METHODS)
        super().__init__(target, service, methods)
        self.chunk_bytes = (stream_chunk_bytes() if chunk_bytes is None
                            else chunk_bytes)
        # None = untried; False = server answered UNIMPLEMENTED (reference
        # PS) — unary forever on this connection
        self._stream_ok: bool | None = None
        # same tri-state for the fused push→barrier→pull method
        self._fused_ok: bool | None = None
        # same-host shared-memory transport (rpc/shm_transport.py): None =
        # negotiation untried; False = permanently downgraded to TCP
        # (UNIMPLEMENTED / refused / attach failure / transport error) —
        # the PR-2 per-connection fallback discipline
        self._shm_conn: shm_transport.ShmClientConnection | None = None
        self._shm_ok: bool | None = None
        self._obs_shm_fallback = obs_stats.counter("rpc.shm.fallback")
        # versioned delta serving (delta/, ISSUE 10): the cached pull
        # this connection patches in place, and the same tri-state
        # downgrade latch as the other extensions — None = untried,
        # False = permanently full-serve (UNIMPLEMENTED / checksum
        # mismatch / version-bookkeeping failure)
        self._delta_state = DeltaPullState()
        self._delta_ok: bool | None = None
        self._obs_delta_rounds = obs_stats.counter("rpc.client.delta.rounds")
        self._obs_delta_bytes = obs_stats.counter("rpc.client.delta.bytes")

    def _streaming(self) -> bool:
        return self.chunk_bytes > 0 and self._stream_ok is not False

    def _fused(self) -> bool:
        return self.chunk_bytes > 0 and self._fused_ok is not False

    @property
    def shm_active(self) -> bool:
        """True once a same-host shared-memory connection is serving the
        fused rounds (worker logging/diagnostics)."""
        return self._shm_conn is not None and self._shm_ok is True

    def close(self) -> None:
        self._drop_shm(permanent=False)
        super().close()

    # ------------------------------------------------------- shm transport
    def _drop_shm(self, permanent: bool = True) -> None:
        conn, self._shm_conn = self._shm_conn, None
        if permanent:
            self._shm_ok = False
        if conn is not None:
            conn.close()

    def _shm_connection(self, timeout):
        """The negotiated shared-memory connection, negotiating on first
        use.  Returns None whenever the fused round should ride TCP —
        permanently after a refusal/UNIMPLEMENTED/attach failure, or just
        for this round when the negotiation RPC itself failed transiently."""
        if not shm_transport.enabled() or self._shm_ok is False:
            return None
        if self._shm_conn is not None:
            return self._shm_conn
        try:
            resp = self.call(
                "NegotiateShm",
                shm_transport.ShmNegotiateRequest(
                    host_id=shm_transport.host_id(),
                    ring_bytes=shm_transport.ring_bytes()),
                timeout=timeout if timeout else 10.0)
        except grpc.RpcError as exc:
            if _status_code(exc) == grpc.StatusCode.UNIMPLEMENTED:
                # reference PS: no such method, TCP forever
                self._shm_ok = False
                self._obs_shm_fallback.add()
                flight.record("shm.downgrade", note="UNIMPLEMENTED")
            return None
        if not resp.accepted:
            log.info("shm transport refused by %s: %s", self._target,
                     resp.message)
            self._shm_ok = False
            self._obs_shm_fallback.add()
            flight.record("shm.downgrade", note="refused")
            return None
        try:
            self._shm_conn = shm_transport.ShmClientConnection(
                resp.c2s_name, resp.s2c_name, int(resp.ring_bytes),
                doorbell_addr=resp.doorbell)
        except (OSError, ValueError, ImportError) as exc:
            # segments not reachable from this process (container /dev/shm
            # isolation, permissions): same-host claim was wrong — TCP
            log.warning("shm segment attach failed (%s); using TCP", exc)
            self._shm_ok = False
            self._obs_shm_fallback.add()
            flight.record("shm.downgrade", note="attach failed")
            return None
        self._shm_ok = True
        log.info("shm transport active to %s (ring %d MB x2)",
                 self._target, int(resp.ring_bytes) >> 20)
        flight.record("shm.attach", b=int(resp.ring_bytes))
        return self._shm_conn

    # ------------------------------------------------------------------ push
    def push_gradients(self, update: m.GradientUpdate,
                       timeout: float | None = None) -> m.PushResponse:
        if not self._streaming():
            return self.call("ReceiveGradients", update, timeout=timeout)

        def chunks() -> Iterator[m.GradientUpdate]:
            # worker_id/iteration ride on every chunk (a handful of bytes);
            # the server reads them off the first.  An empty push still
            # sends ONE empty chunk: under the sharded topology a shard
            # owning none of the pushed tensors must still see the push as
            # a barrier contribution (worker/ps_shards.py).
            sent = False
            for group in split_tensors(update.gradients, self.chunk_bytes):
                sent = True
                yield m.GradientUpdate(worker_id=update.worker_id,
                                       iteration=update.iteration,
                                       gradients=group)
            if not sent:
                yield m.GradientUpdate(worker_id=update.worker_id,
                                       iteration=update.iteration,
                                       gradients=[])

        try:
            resp = self.call("PushGradientsStream", chunks(), timeout=timeout)
            self._stream_ok = True
            return resp
        except grpc.RpcError as exc:
            if _status_code(exc) != grpc.StatusCode.UNIMPLEMENTED:
                raise
            self._stream_ok = False
            return self.call("ReceiveGradients", update, timeout=timeout)

    # ------------------------------------------------------------------ delta
    def _delta(self) -> bool:
        """Whether the version-aware delta protocol should be attempted
        on this connection.  ``delta_enabled`` is read per round so tests
        and operators can flip PSDT_DELTA_DEPTH without rebuilding the
        client; the downgrade latch (UNIMPLEMENTED / checksum mismatch)
        is permanent per connection, like every other extension."""
        return (self.chunk_bytes > 0 and self._delta_ok is not False
                and delta_enabled())

    @property
    def held_version(self) -> int:
        """Store version of the cached pull deltas patch (-1 = none)."""
        return self._delta_state.version

    def _delta_downgrade(self, reason: str) -> None:
        """Permanent per-connection downgrade to the full-serve protocol
        (the PR-2 discipline).  The base may be partially patched after a
        failed apply, so it is dropped unconditionally."""
        self._delta_ok = False
        self._delta_state.invalidate()
        flight.record("serve.delta.downgrade", note=reason[:48])
        log.warning("delta serving permanently downgraded for %s: %s",
                    self._target, reason)

    def _delta_result(self, frames) -> DeltaRoundResult | None:
        """Fold a DeltaFrame stream, translating failures into the
        downgrade discipline: None = the caller must replay via the
        plain protocol (the PS-side per-(worker,tensor) dedup makes the
        replay of an already-landed push exact)."""
        try:
            result = apply_frames(frames, self._delta_state)
        except DeltaBaseMismatch as exc:
            self._delta_downgrade(f"base mismatch: {exc}")
            return None
        self._delta_ok = True
        self._obs_delta_rounds.add()
        if result.served_delta:
            self._obs_delta_bytes.add(result.wire_bytes)
        return result

    def delta_pull(self, request: m.PullRequest,
                   timeout: float | None = None
                   ) -> DeltaRoundResult | None:
        """Version-aware unary pull (``PullParametersDelta``): advertises
        the held version, applies a served delta chain in place against
        the cached pull, and returns the round result (``result.store``
        is the fresh full store either way).  None = use the plain pull
        path (delta disabled or this connection downgraded)."""
        if not self._delta():
            return None
        req = DeltaPullRequest(worker_id=request.worker_id,
                               iteration=request.iteration,
                               wire_dtype=request.wire_dtype,
                               held_version=max(self.held_version, 0))
        try:
            frames = self.call("PullParametersDelta", req, timeout=timeout)
            return self._delta_result(frames)
        except grpc.RpcError as exc:
            if _status_code(exc) == grpc.StatusCode.UNIMPLEMENTED:
                self._delta_downgrade("UNIMPLEMENTED (reference PS)")
                return None
            raise

    def delta_push_pull(self, worker_id: int, iteration: int, tensors_fn,
                        pull_wire_dtype: int = 0,
                        timeout: float | None = None
                        ) -> DeltaRoundResult | None:
        """The version-aware fused round (``PushPullDeltaStream``): the
        ordinary fused chunk stream wrapped with the held version, the
        response a delta chain applied in place (or a stamped full
        serve).  None = run the plain fused round instead — delta
        disabled/downgraded, or the connection prefers the same-host
        shared-memory rings (the shm transport speaks PushPullStream;
        on loopback, zero-copy beats delta byte savings and the wire is
        not the bottleneck anyway)."""
        if not self._delta():
            return None
        if shm_transport.enabled() and self._shm_ok is not False:
            return None
        held = max(self.held_version, 0)

        def chunks() -> Iterator[DeltaPushChunk]:
            # held_version and pull_wire_dtype ride the first chunk only
            # (the server reads header fields off it); an empty push
            # still sends one empty chunk (see push_gradients)
            first = True
            for group in split_tensors(tensors_fn(), self.chunk_bytes):
                yield DeltaPushChunk(
                    update=m.GradientUpdate(
                        worker_id=worker_id, iteration=iteration,
                        gradients=group,
                        pull_wire_dtype=pull_wire_dtype if first else 0),
                    held_version=held if first else 0)
                first = False
            if first:
                yield DeltaPushChunk(
                    update=m.GradientUpdate(worker_id=worker_id,
                                            iteration=iteration,
                                            gradients=[],
                                            pull_wire_dtype=pull_wire_dtype),
                    held_version=held)

        try:
            frames = self.call("PushPullDeltaStream", chunks(),
                               timeout=timeout)
            result = self._delta_result(frames)
        except grpc.RpcError as exc:
            if _status_code(exc) == grpc.StatusCode.UNIMPLEMENTED:
                self._delta_downgrade("UNIMPLEMENTED (reference PS)")
                return None
            raise
        if result is not None:
            # the server just proved it speaks the fused protocol family
            self._fused_ok = True
        return result

    # ------------------------------------------------------------------ fused
    def push_pull(self, worker_id: int, iteration: int, tensors,
                  pull_wire_dtype: int = 0, timeout: float | None = None,
                  on_chunk=None) -> tuple[m.PushResponse,
                                          m.ParameterUpdate | None]:
        """Fused synchronous step over ``PushPullStream``: stream the
        gradient chunks, let the server barrier-wait, receive the fresh
        parameter chunks — one data-plane round.

        ``tensors``: an iterable of wire Tensors, or a ZERO-ARG CALLABLE
        returning a fresh iterator (required when the tensors materialize
        lazily, e.g. bucketed D2H fetch — the unary fallback re-reads
        them, and a half-consumed generator cannot be replayed).
        ``on_chunk``: same contract as :meth:`pull_parameters`.

        Returns ``(push_response, parameter_update | None)``.  The second
        element is ``None`` whenever fresh parameters were NOT delivered
        on this round — fused method unimplemented (reference server),
        push rejected, or server-side barrier timeout — and the caller
        must fall back to its own barrier-wait + pull.  The fallback is
        remembered per connection, exactly like the chunk-stream RPCs."""
        tensors_fn = tensors if callable(tensors) else lambda: iter(tensors)
        if not self._fused():
            return self._push_only(worker_id, iteration, tensors_fn,
                                   timeout), None

        def chunks() -> Iterator[m.GradientUpdate]:
            # pull_wire_dtype rides the first chunk only (the server reads
            # header fields off it); an empty push still sends one empty
            # chunk — the sharded-topology barrier invariant (see
            # push_gradients)
            first = True
            for group in split_tensors(tensors_fn(), self.chunk_bytes):
                yield m.GradientUpdate(
                    worker_id=worker_id, iteration=iteration,
                    gradients=group,
                    pull_wire_dtype=pull_wire_dtype if first else 0)
                first = False
            if first:
                yield m.GradientUpdate(worker_id=worker_id,
                                       iteration=iteration, gradients=[],
                                       pull_wire_dtype=pull_wire_dtype)

        # Same-host fast path: the SAME chunk messages, byte-encoded into
        # the shared-memory rings instead of the gRPC channel.  Any shm
        # failure downgrades this connection to TCP permanently and the
        # round is replayed below (tensors_fn is replayable by contract).
        conn = self._shm_connection(timeout)
        if conn is not None:
            # a shm round IS a fused PushPullStream round, just not over
            # gRPC: count it under the same call/latency instruments so
            # rounds-per-step accounting stays transport-independent
            # (payload bytes land in rpc.shm.bytes instead), give it the
            # same client span, and stamp the trace context on every
            # chunk — the ring transport bypasses RpcClient.call, which
            # is where the field-999 plumbing normally happens
            calls, latency, _ = self._instruments["PushPullStream"]
            calls.add()
            t0 = time.perf_counter()
            flight.record("rpc.cli.start", note="PushPull/shm")
            ok = False
            try:
                with obs_trace.span("rpc/client/PushPullStream",
                                    target=self._target, transport="shm"):
                    ctx = obs_trace.wire_context()

                    def encoded_frames() -> Iterator[bytes]:
                        for chunk in chunks():
                            if ctx:
                                chunk.trace_context = ctx
                            yield chunk.encode()

                    frames = conn.round_trip(encoded_frames(), timeout)
                    result = self._assemble_fused(
                        (m.PushPullResponse.decode(memoryview(f))
                         for f in frames), on_chunk)
                # the server just proved it speaks the fused protocol
                self._fused_ok = True
                ok = True
                return result
            except shm_transport.ShmTransportError as exc:
                log.warning("shm fused round failed (%s); permanently "
                            "downgrading %s to TCP", exc, self._target)
                flight.record("shm.downgrade", note="round failed")
                self._obs_shm_fallback.add()
                self._drop_shm()
            finally:
                latency.observe(time.perf_counter() - t0)
                flight.record("rpc.cli.end",
                              a=int(1e6 * (time.perf_counter() - t0)),
                              b=1 if ok else 0, note="PushPull/shm")

        try:
            result = self._assemble_fused(
                self.call("PushPullStream", chunks(), timeout=timeout),
                on_chunk)
            self._fused_ok = True
            return result
        except grpc.RpcError as exc:
            if _status_code(exc) != grpc.StatusCode.UNIMPLEMENTED:
                raise
            self._fused_ok = False
            return self._push_only(worker_id, iteration, tensors_fn,
                                   timeout), None

    @staticmethod
    def _assemble_fused(frames, on_chunk) -> tuple[m.PushResponse,
                                                   m.ParameterUpdate | None]:
        """Fold a ``PushPullResponse`` frame stream (gRPC call or decoded
        shm frames — identical bytes, identical semantics) into the
        ``(push, params | None)`` result."""
        push: m.PushResponse | None = None
        merged: list[m.Tensor] = []
        params_iteration, ready, got_params = 0, False, False
        for frame in frames:
            if frame.push is not None and push is None:
                push = frame.push
            if frame.params is not None:
                got_params = True
                chunk = frame.params
                params_iteration, ready = chunk.iteration, chunk.ready
                if on_chunk is not None:
                    on_chunk(chunk.parameters)
                    merged.extend(
                        m.Tensor(name=t.name,
                                 packed_dtype=t.packed_dtype)
                        for t in chunk.parameters)
                else:
                    merged.extend(chunk.parameters)
        if push is None:
            return m.PushResponse(success=False,
                                  message="empty fused response"), None
        if not (got_params and ready):
            return push, None
        return push, m.ParameterUpdate(iteration=params_iteration,
                                       parameters=merged, ready=True)

    def _push_only(self, worker_id: int, iteration: int, tensors_fn,
                   timeout) -> m.PushResponse:
        """Degraded fused call: push leg only (chunk-streamed when the
        server supports it, unary otherwise); the caller supplies the
        barrier-wait and pull."""
        update = m.GradientUpdate(worker_id=worker_id, iteration=iteration,
                                  gradients=list(tensors_fn()))
        return self.push_gradients(update, timeout=timeout)

    # ------------------------------------------------------------------ pull
    def pull_parameters(self, request: m.PullRequest,
                        timeout: float | None = None,
                        on_chunk=None) -> m.ParameterUpdate:
        """Returns one merged ParameterUpdate (chunks are concatenated in
        server order, so the result is indistinguishable from the unary
        response).

        ``on_chunk(tensors)``: optional per-chunk consumer called as each
        chunk ARRIVES — the worker converts tensors to f32 arrays there,
        overlapping conversion with the transport of later chunks.  The
        consumed tensors still appear in the returned message (the
        consumer must not mutate them); on the unary fallback it is
        called once with the whole list, so callers behave identically
        either way."""
        def unary_pull() -> m.ParameterUpdate:
            resp = self.call("ServeParameters", request, timeout=timeout)
            if on_chunk is not None:
                on_chunk(resp.parameters)
            return resp

        if not self._streaming():
            return unary_pull()
        try:
            chunks = self.call("ServeParametersStream", request,
                               timeout=timeout)
            merged: list[m.Tensor] = []
            iteration, ready = 0, False
            got_any = False
            for chunk in chunks:
                got_any = True
                iteration, ready = chunk.iteration, chunk.ready
                if on_chunk is not None:
                    on_chunk(chunk.parameters)
                    # the consumer took the payloads; retain only the
                    # metadata callers read off the response (name +
                    # packed_dtype for wire negotiation) — holding the
                    # full wire copy alongside the converted store would
                    # double peak pull memory at GB scale
                    merged.extend(
                        m.Tensor(name=t.name, packed_dtype=t.packed_dtype)
                        for t in chunk.parameters)
                else:
                    merged.extend(chunk.parameters)
            self._stream_ok = True
            if not got_any:  # zero-chunk stream: treat as an empty store
                return unary_pull()
            return m.ParameterUpdate(iteration=iteration, parameters=merged,
                                     ready=ready)
        except grpc.RpcError as exc:
            if _status_code(exc) != grpc.StatusCode.UNIMPLEMENTED:
                raise
            self._stream_ok = False
            return unary_pull()
