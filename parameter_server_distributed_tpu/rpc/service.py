"""gRPC plumbing: generic service binding + typed clients.

The reference generates C++ service/stub classes with grpc_cpp_plugin
(reference: CMakeLists.txt:87-113).  Here the equivalent binding is done at
runtime through gRPC's generic-handler API with the wire codec from
`wire.py`, so no gencode is needed while remaining wire-compatible with the
reference's services (method paths `/parameter_server.ParameterServer/<M>`
and `/coordinator.Coordinator/<M>`).

One deliberate departure: the reference opens a **fresh channel per call**
on the worker hot path (reference: src/worker.cpp:241, 255, 275, 219) —
connection setup per RPC.  Clients here hold one persistent channel.
"""

from __future__ import annotations

import concurrent.futures
from typing import Any, Callable, Mapping

import grpc

from .wire import Message


def bind_service(server: grpc.Server, service_name: str,
                 methods: Mapping[str, tuple[type[Message], type[Message]]],
                 impl: Any) -> None:
    """Register ``impl`` on ``server``: for each method M, ``impl.M(request,
    context)`` must exist and return the response message."""
    handlers = {}
    for method, (req_cls, resp_cls) in methods.items():
        handlers[method] = grpc.unary_unary_rpc_method_handler(
            getattr(impl, method),
            request_deserializer=req_cls.decode,
            response_serializer=lambda msg: msg.encode(),
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(service_name, handlers),))


def make_server(max_workers: int = 8) -> grpc.Server:
    return grpc.server(
        concurrent.futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            ("grpc.max_send_message_length", 1 << 30),
            ("grpc.max_receive_message_length", 1 << 30),
        ])


class RpcClient:
    """Typed unary-unary client over one persistent insecure channel
    (the reference uses insecure channels throughout —
    src/worker.cpp:143, parameter_server_service.cpp:181)."""

    def __init__(self, target: str, service_name: str,
                 methods: Mapping[str, tuple[type[Message], type[Message]]]):
        self._channel = grpc.insecure_channel(target, options=[
            ("grpc.max_send_message_length", 1 << 30),
            ("grpc.max_receive_message_length", 1 << 30),
        ])
        self._calls: dict[str, Callable] = {}
        for method, (req_cls, resp_cls) in methods.items():
            self._calls[method] = self._channel.unary_unary(
                f"/{service_name}/{method}",
                request_serializer=lambda msg: msg.encode(),
                response_deserializer=resp_cls.decode,
            )

    def call(self, method: str, request: Message, timeout: float | None = None):
        return self._calls[method](request, timeout=timeout)

    def close(self) -> None:
        self._channel.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
