"""gRPC plumbing: generic service binding + typed clients.

The reference generates C++ service/stub classes with grpc_cpp_plugin
(reference: CMakeLists.txt:87-113).  Here the equivalent binding is done at
runtime through gRPC's generic-handler API with the wire codec from
`wire.py`, so no gencode is needed while remaining wire-compatible with the
reference's services (method paths `/parameter_server.ParameterServer/<M>`
and `/coordinator.Coordinator/<M>`).

One deliberate departure: the reference opens a **fresh channel per call**
on the worker hot path (reference: src/worker.cpp:241, 255, 275, 219) —
connection setup per RPC.  Clients here hold one persistent channel.
"""

from __future__ import annotations

import concurrent.futures
from typing import Any, Callable, Mapping

import grpc

from .wire import Message


def _spec(entry) -> tuple[type[Message], type[Message], str]:
    """Normalize a method-table entry: (req, resp) -> unary-unary, or
    (req, resp, style) with style in unary | stream_unary | unary_stream."""
    if len(entry) == 2:
        req_cls, resp_cls = entry
        return req_cls, resp_cls, "unary"
    req_cls, resp_cls, style = entry
    return req_cls, resp_cls, style


def bind_service(server: grpc.Server, service_name: str,
                 methods: Mapping[str, tuple],
                 impl: Any) -> None:
    """Register ``impl`` on ``server``: for each method M, ``impl.M(request,
    context)`` must exist and return the response message (for
    ``stream_unary`` the first argument is a request iterator; for
    ``unary_stream`` the method returns an iterator of responses)."""
    handlers = {}
    for method, entry in methods.items():
        req_cls, resp_cls, style = _spec(entry)
        make_handler = {
            "unary": grpc.unary_unary_rpc_method_handler,
            "stream_unary": grpc.stream_unary_rpc_method_handler,
            "unary_stream": grpc.unary_stream_rpc_method_handler,
        }[style]
        handlers[method] = make_handler(
            getattr(impl, method),
            request_deserializer=req_cls.decode,
            response_serializer=lambda msg: msg.encode(),
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(service_name, handlers),))


# Shared channel/server options.  The HTTP/2 tuning matters for the bulk
# data plane: the default 16KB frame size caps loopback/LAN throughput at a
# fraction of line rate for tensor-sized messages (measured ~2x on streamed
# chunks with 16MB frames); the larger write buffer keeps the transport fed
# while the next chunk encodes.
CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", 1 << 30),
    ("grpc.max_receive_message_length", 1 << 30),
    ("grpc.http2.max_frame_size", 16 << 20),
    ("grpc.http2.write_buffer_size", 64 << 20),
]


def make_server(max_workers: int = 8) -> grpc.Server:
    return grpc.server(
        concurrent.futures.ThreadPoolExecutor(max_workers=max_workers),
        options=CHANNEL_OPTIONS)


class RpcClient:
    """Typed unary-unary client over one persistent insecure channel
    (the reference uses insecure channels throughout —
    src/worker.cpp:143, parameter_server_service.cpp:181)."""

    def __init__(self, target: str, service_name: str,
                 methods: Mapping[str, tuple]):
        self._channel = grpc.insecure_channel(target,
                                              options=CHANNEL_OPTIONS)
        self._calls: dict[str, Callable] = {}
        for method, entry in methods.items():
            req_cls, resp_cls, style = _spec(entry)
            make_call = {
                "unary": self._channel.unary_unary,
                "stream_unary": self._channel.stream_unary,
                "unary_stream": self._channel.unary_stream,
            }[style]
            self._calls[method] = make_call(
                f"/{service_name}/{method}",
                request_serializer=lambda msg: msg.encode(),
                response_deserializer=resp_cls.decode,
            )

    def call(self, method: str, request: Message, timeout: float | None = None):
        """Unary call.  For a ``stream_unary`` method pass an ITERATOR of
        request messages (gRPC pulls it from a sender thread, so per-chunk
        encode overlaps transport); a ``unary_stream`` method returns an
        iterator of response messages that decode as chunks arrive."""
        return self._calls[method](request, timeout=timeout)

    def close(self) -> None:
        self._channel.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
