"""gRPC plumbing: generic service binding + typed clients.

The reference generates C++ service/stub classes with grpc_cpp_plugin
(reference: CMakeLists.txt:87-113).  Here the equivalent binding is done at
runtime through gRPC's generic-handler API with the wire codec from
`wire.py`, so no gencode is needed while remaining wire-compatible with the
reference's services (method paths `/parameter_server.ParameterServer/<M>`
and `/coordinator.Coordinator/<M>`).

One deliberate departure: the reference opens a **fresh channel per call**
on the worker hot path (reference: src/worker.cpp:241, 255, 275, 219) —
connection setup per RPC.  Clients here hold one persistent channel.

Both ends of every RPC are instrumented through the observability
subsystem (obs/): per-method call counts, latency histograms, and
request/response byte counters are always on (a few dict ops per call —
bounded overhead), and when tracing is enabled the client opens a span
whose context rides the request's extension field so the server handler's
span joins the caller's trace (obs/trace.py).  Latency for a
``unary_stream`` client call covers dispatch only (the response iterator
outlives the call); byte counters still see every chunk because they live
in the (de)serializers.
"""

from __future__ import annotations

import concurrent.futures
import time
from typing import Any, Callable, Iterator, Mapping

import grpc

from ..obs import flight
from ..obs import stats as obs_stats
from ..obs import trace as obs_trace
from .wire import Message


def _spec(entry) -> tuple[type[Message], type[Message], str]:
    """Normalize a method-table entry: (req, resp) -> unary-unary, or
    (req, resp, style) with style in unary | stream_unary | unary_stream
    | stream_stream."""
    if len(entry) == 2:
        req_cls, resp_cls = entry
        return req_cls, resp_cls, "unary"
    req_cls, resp_cls, style = entry
    return req_cls, resp_cls, style


def _counting_deserializer(decode: Callable, counter) -> Callable:
    def deserialize(buf):
        counter.add(len(buf))
        return decode(buf)
    return deserialize


def _counting_serializer(counter) -> Callable:
    def serialize(msg: Message) -> bytes:
        data = msg.encode()
        counter.add(len(data))
        return data
    return serialize


def _instrument_handler(behavior: Callable, method: str, style: str):
    """Wrap a service method with call/latency accounting and a server
    span that adopts the caller's trace context (when the request message
    carries the extension field and tracing is on)."""
    calls = obs_stats.counter(f"rpc.server.{method}.calls")
    latency = obs_stats.histogram(f"rpc.server.{method}.latency_s")
    span_name = f"rpc/server/{method}"

    def flight_end(t0: float) -> None:
        # both-ends flight evidence: the handler's end stamp with its
        # wall time — a crash mid-handler leaves the start stamp open,
        # which is exactly the "in flight at death" witness
        flight.record("rpc.srv.end", a=int(1e6 * (time.perf_counter() - t0)),
                      note=method)

    if style == "stream_unary":
        def stream_unary(request_iterator, context):
            calls.add()
            t0 = time.perf_counter()
            flight.record("rpc.srv.start", note=method)
            # the remote context arrives on the FIRST chunk, after the
            # handler has started — SpanHolder defers adoption
            holder = obs_trace.SpanHolder(span_name)

            def chunks():
                for req in request_iterator:
                    holder.adopt(getattr(req, "trace_context", b""))
                    yield req

            try:
                return behavior(chunks(), context)
            finally:
                holder.finish()
                latency.observe(time.perf_counter() - t0)
                flight_end(t0)
        return stream_unary

    if style == "stream_stream":
        def stream_stream(request_iterator, context):
            calls.add()
            t0 = time.perf_counter()
            flight.record("rpc.srv.start", note=method)
            # like stream_unary, the remote context arrives on the first
            # request chunk, after the handler has started
            holder = obs_trace.SpanHolder(span_name)

            def chunks():
                for req in request_iterator:
                    holder.adopt(getattr(req, "trace_context", b""))
                    yield req

            def stream():
                try:
                    yield from behavior(chunks(), context)
                finally:
                    holder.finish()
                    latency.observe(time.perf_counter() - t0)
                    flight_end(t0)
            return stream()
        return stream_stream

    if style == "unary_stream":
        def unary_stream(request, context):
            calls.add()
            t0 = time.perf_counter()
            flight.record("rpc.srv.start", note=method)
            ctx = getattr(request, "trace_context", b"")

            def stream():
                try:
                    with obs_trace.server_span(span_name, ctx):
                        yield from behavior(request, context)
                finally:
                    latency.observe(time.perf_counter() - t0)
                    flight_end(t0)
            return stream()
        return unary_stream

    def unary(request, context):
        calls.add()
        t0 = time.perf_counter()
        flight.record("rpc.srv.start", note=method)
        try:
            with obs_trace.server_span(
                    span_name, getattr(request, "trace_context", b"")):
                return behavior(request, context)
        finally:
            latency.observe(time.perf_counter() - t0)
            flight_end(t0)
    return unary


def bind_service(server: grpc.Server, service_name: str,
                 methods: Mapping[str, tuple],
                 impl: Any) -> None:
    """Register ``impl`` on ``server``: for each method M, ``impl.M(request,
    context)`` must exist and return the response message (for
    ``stream_unary`` the first argument is a request iterator; for
    ``unary_stream`` the method returns an iterator of responses)."""
    handlers = {}
    for method, entry in methods.items():
        req_cls, resp_cls, style = _spec(entry)
        make_handler = {
            "unary": grpc.unary_unary_rpc_method_handler,
            "stream_unary": grpc.stream_unary_rpc_method_handler,
            "unary_stream": grpc.unary_stream_rpc_method_handler,
            "stream_stream": grpc.stream_stream_rpc_method_handler,
        }[style]
        handlers[method] = make_handler(
            _instrument_handler(getattr(impl, method), method, style),
            request_deserializer=_counting_deserializer(
                req_cls.decode,
                obs_stats.counter(f"rpc.server.{method}.request_bytes")),
            response_serializer=_counting_serializer(
                obs_stats.counter(f"rpc.server.{method}.response_bytes")),
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(service_name, handlers),))


# Shared channel/server options.  The HTTP/2 tuning matters for the bulk
# data plane: the default 16KB frame size caps loopback/LAN throughput at a
# fraction of line rate for tensor-sized messages (measured ~2x on streamed
# chunks with 16MB frames); the larger write buffer keeps the transport fed
# while the next chunk encodes.
CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", 1 << 30),
    ("grpc.max_receive_message_length", 1 << 30),
    ("grpc.http2.max_frame_size", 16 << 20),
    ("grpc.http2.write_buffer_size", 64 << 20),
]


def status_code(exc: grpc.RpcError):
    """Status code of an RpcError, or None for errors that carry none
    (e.g. fault-injection stubs raising bare grpc.RpcError)."""
    code = getattr(exc, "code", None)
    return code() if callable(code) else None


def make_server(max_workers: int = 8) -> grpc.Server:
    return grpc.server(
        concurrent.futures.ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="rpc-handler"),
        options=CHANNEL_OPTIONS)


def _inject_stream(request_iterator, ctx: bytes) -> Iterator[Message]:
    """Stamp the trace context on every chunk of a client-streamed request
    (gRPC pulls the iterator from its own sender thread, so the context is
    captured eagerly on the calling thread)."""
    for req in request_iterator:
        if hasattr(req, "trace_context"):
            req.trace_context = ctx
        yield req


class RpcClient:
    """Typed unary-unary client over one persistent insecure channel
    (the reference uses insecure channels throughout —
    src/worker.cpp:143, parameter_server_service.cpp:181)."""

    def __init__(self, target: str, service_name: str,
                 methods: Mapping[str, tuple]):
        self._target = target
        self._channel = grpc.insecure_channel(target,
                                              options=CHANNEL_OPTIONS)
        self._calls: dict[str, Callable] = {}
        # per-method instruments, resolved once (registry lookups are
        # locked dict ops; the hot path should only touch the instruments)
        self._instruments: dict[str, tuple] = {}
        for method, entry in methods.items():
            req_cls, resp_cls, style = _spec(entry)
            make_call = {
                "unary": self._channel.unary_unary,
                "stream_unary": self._channel.stream_unary,
                "unary_stream": self._channel.unary_stream,
                "stream_stream": self._channel.stream_stream,
            }[style]
            self._calls[method] = make_call(
                f"/{service_name}/{method}",
                request_serializer=_counting_serializer(
                    obs_stats.counter(f"rpc.client.{method}.request_bytes")),
                response_deserializer=_counting_deserializer(
                    resp_cls.decode,
                    obs_stats.counter(
                        f"rpc.client.{method}.response_bytes")),
            )
            self._instruments[method] = (
                obs_stats.counter(f"rpc.client.{method}.calls"),
                obs_stats.histogram(f"rpc.client.{method}.latency_s"),
                style)

    def call(self, method: str, request: Message, timeout: float | None = None):
        """Unary call.  For a ``stream_unary`` or ``stream_stream`` method
        pass an ITERATOR of request messages (gRPC pulls it from a sender
        thread, so per-chunk encode overlaps transport); ``unary_stream``
        and ``stream_stream`` return an iterator of response messages that
        decode as chunks arrive."""
        calls, latency, style = self._instruments[method]
        calls.add()
        t0 = time.perf_counter()
        flight.record("rpc.cli.start", note=method)
        ok = False
        try:
            if not obs_trace.enabled():
                resp = self._calls[method](request, timeout=timeout)
                ok = True
                return resp
            with obs_trace.span(f"rpc/client/{method}", target=self._target):
                ctx = obs_trace.wire_context()
                if style in ("stream_unary", "stream_stream"):
                    request = _inject_stream(request, ctx)
                elif ctx and hasattr(request, "trace_context"):
                    request.trace_context = ctx
                resp = self._calls[method](request, timeout=timeout)
                ok = True
                return resp
        finally:
            latency.observe(time.perf_counter() - t0)
            flight.record("rpc.cli.end",
                          a=int(1e6 * (time.perf_counter() - t0)),
                          b=1 if ok else 0, note=method)

    def close(self) -> None:
        self._channel.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
