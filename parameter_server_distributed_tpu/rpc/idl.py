"""Emit proto3 IDL text from the declarative message schemas.

`rpc/messages.py` is the single source of truth for the wire contract
(field numbers/types mirroring the reference IDL — reference
proto/parameter_server.proto, proto/coordinator.proto).  This module
renders that contract back out as `.proto` files so that

- a C++/Go peer can `protoc`-compile against this framework without the
  reference checkout (``python -m parameter_server_distributed_tpu.rpc.idl
  <outdir>``), and
- the wire-interop test suite can cross-check our hand-rolled codec
  against protoc gencode even where the reference protos are absent
  (e.g. public CI).

The emitted text includes the framework's extension fields (Tensor 5/6,
PullRequest 3, GetPSAddressResponse 3); reference peers skip those per
proto3 unknown-field rules.
"""

from __future__ import annotations

from . import messages as m

_SCALAR = {"int32": "int32", "int64": "int64", "bool": "bool",
           "float": "float", "string": "string", "bytes": "bytes"}

# The only enum in either package; field kind "enum" maps to its type name.
_ENUM_NAME = "WorkerStatus"

PACKAGES = {
    "parameter_server": {
        "messages": (m.GradientUpdate, m.Tensor, m.PushResponse,
                     m.PullRequest, m.ParameterUpdate, m.SyncStatusRequest,
                     m.SyncStatusResponse, m.SaveCheckpointRequest,
                     m.SaveCheckpointResponse, m.LoadCheckpointRequest,
                     m.LoadCheckpointResponse),
        "enums": (),
        "service": ("ParameterServer", m.PARAMETER_SERVER_METHODS),
    },
    "coordinator": {
        "messages": (m.WorkerInfo, m.RegisterResponse, m.HeartbeatRequest,
                     m.HeartbeatResponse, m.ListWorkersRequest,
                     m.ListWorkersResponse, m.GetPSAddressRequest,
                     m.GetPSAddressResponse),
        "enums": (m.WorkerStatus,),
        "service": ("Coordinator", m.COORDINATOR_METHODS),
    },
}


def _field_line(f) -> str:
    if f.kind == "message":
        type_name = f.message_type.__name__
    elif f.kind == "enum":
        type_name = _ENUM_NAME
    else:
        type_name = _SCALAR[f.kind]
    repeated = "repeated " if f.repeated else ""
    return f"  {repeated}{type_name} {f.name} = {f.number};"


def render_package(package: str) -> str:
    spec = PACKAGES[package]
    service_name, methods = spec["service"]
    out = ["syntax = \"proto3\";", "", f"package {package};", ""]
    out.append(f"service {service_name} {{")
    for method, (req, resp) in methods.items():
        out.append(f"  rpc {method}({req.__name__}) "
                   f"returns ({resp.__name__});")
    out.append("}")
    for enum in spec["enums"]:
        out += ["", f"enum {enum.__name__} {{"]
        for value, name in sorted(enum._NAMES.items()):
            out.append(f"  {name} = {value};")
        out.append("}")
    for msg in spec["messages"]:
        out += ["", f"message {msg.__name__} {{"]
        out += [_field_line(f) for f in msg.FIELDS]
        out.append("}")
    return "\n".join(out) + "\n"


def write_protos(outdir: str) -> list[str]:
    import os

    os.makedirs(outdir, exist_ok=True)
    paths = []
    for package in PACKAGES:
        path = os.path.join(outdir, f"{package}.proto")
        with open(path, "w") as fh:
            fh.write(render_package(package))
        paths.append(path)
    return paths


if __name__ == "__main__":
    import sys

    for p in write_protos(sys.argv[1] if len(sys.argv) > 1 else "."):
        print(p)
