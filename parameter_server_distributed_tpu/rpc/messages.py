"""Wire-compatible message schemas for the two control-plane services.

Field numbers, types, and service/method names mirror the reference IDL so
that this framework's control plane interoperates at the wire level with the
reference's C++ clients and servers:

- ParameterServer service (5 RPCs): reference proto/parameter_server.proto:5-11
- Coordinator service (4 RPCs):     reference proto/coordinator.proto:5-10

Messages are declared with the declarative codec in `wire.py` rather than
protoc gencode.  `Tensor.data` is held as a numpy float32 array end-to-end
(packed `repeated float` on the wire — reference proto/parameter_server.proto:22),
so tensor payloads never pass through per-element Python objects.
"""

from __future__ import annotations

import numpy as np

# Wire-dtype constants and the payload codec live in codec.py (the byte
# work is implementation, not schema); they are re-exported here because
# this module is the wire contract's public face (the analyzer manifest
# pins their VALUES via WIRE_DTYPE_NAMES below).
from .codec import (PACKED_WIRE_DTYPES, TOPK_DEFAULT_DENSITY, WIRE_BF16,
                    WIRE_DTYPE_NAMES, WIRE_F32, WIRE_INT8, WIRE_RAW_F32,
                    WIRE_TOPK, active_codec, bf16_dtype as _bf16_dtype,
                    topk_k)
from .wire import ArrayPayload, Field, Message

# --------------------------------------------------------------------------
# parameter_server package
# --------------------------------------------------------------------------

DTYPE_FLOAT32 = 0
DTYPE_FLOAT64 = 1  # declared by the reference IDL, never used by its runtime

# WIRE_F32 is the reference encoding (packed `repeated float`, field 3).
# The packed encodings (see codec.py for layouts) are a framework extension
# carried in fields 5/6, which reference peers skip per proto3
# unknown-field rules; they are only emitted when a peer asks for them.
# WIRE_DTYPE_NAMES re-exported above — one definition, in codec.py.


class Tensor(Message):
    """Named dense tensor (reference proto/parameter_server.proto:19-24).

    Fields 1-4 mirror the reference IDL.  Fields 5/6 are the packed-payload
    extension: when `packed_dtype` != WIRE_F32 the flat data rides in the
    `packed` bytes blob (bf16 halves push/pull bytes) and field 3 is empty.
    """
    FIELDS = (
        Field(1, "name", "string"),
        Field(2, "shape", "int32", repeated=True),
        Field(3, "data", "float", repeated=True),
        Field(4, "dtype", "int32"),
        Field(5, "packed", "bytes"),
        Field(6, "packed_dtype", "int32"),
    )

    @classmethod
    def from_array(cls, name: str, array: np.ndarray,
                   wire_dtype: int = WIRE_F32,
                   topk_density: float = TOPK_DEFAULT_DENSITY) -> "Tensor":
        # float64 inputs are marked dtype=1 (the reference IDL's declared
        # float64 — proto/parameter_server.proto:23) but still ride the
        # wire as `repeated float`, exactly as a reference peer would emit
        # them (its tensor struct stores vector<float> regardless of dtype).
        src = np.asarray(array)
        dtype_tag = (DTYPE_FLOAT64 if src.dtype == np.float64
                     else DTYPE_FLOAT32)
        arr = src.astype(np.float32, copy=False)  # zero-copy for f32 input
        if wire_dtype not in PACKED_WIRE_DTYPES:
            return cls(name=name, shape=list(arr.shape),
                       data=arr.reshape(-1), dtype=dtype_tag)
        flat = arr.reshape(-1)
        k = 0
        if wire_dtype == WIRE_TOPK:
            if flat.size >= 2**32:
                # u4 wire indices would silently wrap on decode; no real
                # tensor is 4B+ elements (16 GB+ f32), so refuse loudly
                # rather than degrade to a quiet corruption.
                raise ValueError(
                    f"WIRE_TOPK indices are u32: tensor {name!r} has "
                    f"{flat.size} elements (>= 2**32); use bf16 wire")
            k = topk_k(flat.size, topk_density)
        # lazy payload for EVERY packed encoding: the cast / int8 quantize /
        # top-k sparsify runs through the active codec (native C++ under
        # PSDT_NATIVE) straight into the outgoing message buffer at encode
        # time (wire.ArrayPayload.pack_into)
        return cls(name=name, shape=list(arr.shape), dtype=dtype_tag,
                   packed=ArrayPayload(flat, wire_dtype, k),
                   packed_dtype=wire_dtype)

    def to_array(self) -> np.ndarray:
        packed = self.packed
        if isinstance(packed, ArrayPayload):
            # locally-built tensor read back without a wire round-trip:
            # materialize the exact bytes the wire would carry so the value
            # matches what a remote peer would decode (bf16 quantization
            # included)
            packed = packed.tobytes()
        if self.packed_dtype in PACKED_WIRE_DTYPES and packed:
            # np.prod([]) == 1: an empty shape list is a 0-d SCALAR (one
            # element), not an empty tensor — empty tensors carry [0]
            # (the dense total only matters to WIRE_TOPK's scatter)
            arr = active_codec().unpack(self.packed_dtype, packed,
                                        int(np.prod(self.shape)))
        else:
            arr = np.asarray(self.data, dtype=np.float32)
        if self.dtype == DTYPE_FLOAT64:
            # honor the reference IDL's declared float64 tag: upcast so a
            # dtype=1 tensor round-trips at the precision the sender marked
            # (wire payload itself is float-precision, as in the reference)
            arr = arr.astype(np.float64)
        if not arr.flags.writeable:
            # decode paths can yield frombuffer views (zero-copy); callers
            # get writable arrays so in-place aggregation works uniformly
            arr = arr.copy()
        if self.shape:
            arr = arr.reshape(self.shape)
        return arr


# Observability extension (obs/trace.py): request messages of the traced
# data/control path carry the caller's span context in high-numbered field
# 999 — b"trace_id/span_id".  Reference peers skip the unknown field per
# proto3 rules (tests/test_wire_interop.py), and the field elides entirely
# when tracing is off, keeping the bytes reference-identical.
TRACE_FIELD_NUMBER = 999


class GradientUpdate(Message):
    """Fields 1-3 mirror the reference IDL.  Field 4 is a framework
    extension read only by the fused ``PushPullStream`` data plane
    (rpc/data_plane.py): the wire encoding (WIRE_*) the pushing worker
    wants the post-barrier parameters streamed back in — the fused round
    has no separate PullRequest to carry it.  Reference peers skip it per
    proto3 unknown-field rules; the unary/stream push paths never set it."""
    FIELDS = (
        Field(1, "worker_id", "int32"),
        Field(2, "iteration", "int32"),
        Field(3, "gradients", "message", message_type=Tensor, repeated=True),
        Field(4, "pull_wire_dtype", "int32"),
        Field(TRACE_FIELD_NUMBER, "trace_context", "bytes"),
    )


class PushResponse(Message):
    FIELDS = (
        Field(1, "success", "bool"),
        Field(2, "message", "string"),
        Field(3, "iteration", "int32"),
        Field(4, "aggregation_complete", "bool"),
        Field(5, "workers_received", "int32"),
        Field(6, "total_workers", "int32"),
    )


class PullRequest(Message):
    """Field 3 is a framework extension: the wire encoding the client wants
    served parameters in (WIRE_*).  Reference servers skip it and serve
    repeated-float; reference clients never set it and get the default."""
    FIELDS = (
        Field(1, "worker_id", "int32"),
        Field(2, "iteration", "int32"),
        Field(3, "wire_dtype", "int32"),
        Field(TRACE_FIELD_NUMBER, "trace_context", "bytes"),
    )


class ParameterUpdate(Message):
    FIELDS = (
        Field(1, "iteration", "int32"),
        Field(2, "parameters", "message", message_type=Tensor, repeated=True),
        Field(3, "ready", "bool"),
    )


class PushPullResponse(Message):
    """One frame of the fused ``PushPullStream`` response (framework
    extension, rpc/data_plane.py).  Exactly one of the two sub-messages is
    set per frame: the FIRST frame carries ``push`` (the push verdict, sent
    the instant the gradients are applied so a stale rejection never waits
    on the barrier); every later frame carries ``params`` (a chunk of the
    post-barrier parameter stream, same schema as the unary pull)."""
    FIELDS = (
        Field(1, "push", "message", message_type=PushResponse),
        Field(2, "params", "message", message_type=ParameterUpdate),
    )


class SyncStatusRequest(Message):
    FIELDS = (
        Field(1, "iteration", "int32"),
        Field(TRACE_FIELD_NUMBER, "trace_context", "bytes"),
    )


class SyncStatusResponse(Message):
    FIELDS = (
        Field(1, "iteration", "int32"),
        Field(2, "ready", "bool"),
        Field(3, "workers_received", "int32"),
        Field(4, "total_workers", "int32"),
    )


class SaveCheckpointRequest(Message):
    FIELDS = (
        Field(1, "epoch", "int32"),
        Field(2, "path", "string"),
    )


class SaveCheckpointResponse(Message):
    FIELDS = (
        Field(1, "success", "bool"),
        Field(2, "message", "string"),
        Field(3, "checkpoint_path", "string"),
    )


class LoadCheckpointRequest(Message):
    FIELDS = (Field(1, "path", "string"),)


class LoadCheckpointResponse(Message):
    FIELDS = (
        Field(1, "success", "bool"),
        Field(2, "message", "string"),
        Field(3, "epoch", "int32"),
        Field(4, "parameters", "message", message_type=Tensor, repeated=True),
    )


# --------------------------------------------------------------------------
# coordinator package
# --------------------------------------------------------------------------

class WorkerStatus:
    """Enum (reference proto/coordinator.proto:31-36)."""
    IDLE = 0
    TRAINING = 1
    CHECKPOINTING = 2
    ERROR = 3

    _NAMES = {0: "IDLE", 1: "TRAINING", 2: "CHECKPOINTING", 3: "ERROR"}

    @classmethod
    def name(cls, value: int) -> str:
        return cls._NAMES.get(value, f"UNKNOWN({value})")


class WorkerInfo(Message):
    FIELDS = (
        Field(1, "worker_id", "int32"),
        Field(2, "address", "string"),
        Field(3, "port", "int32"),
        Field(4, "hostname", "string"),
    )


class RegisterResponse(Message):
    FIELDS = (
        Field(1, "success", "bool"),
        Field(2, "message", "string"),
        Field(3, "parameter_server_address", "string"),
        Field(4, "total_workers", "int32"),
    )


class HeartbeatRequest(Message):
    """Field 999 is a framework extension: a JSON metric snapshot of the
    worker's obs registry (obs/export.snapshot_blob), piggybacked on the
    existing heartbeat cadence so cluster metrics need no extra RPC from
    the workers.  Reference coordinators skip it per proto3 unknown-field
    rules."""
    FIELDS = (
        Field(1, "worker_id", "int32"),
        Field(2, "status", "enum"),
        Field(999, "obs_snapshot", "bytes"),
    )


class HeartbeatResponse(Message):
    FIELDS = (
        Field(1, "success", "bool"),
        Field(2, "timestamp", "int64"),
    )


class ListWorkersRequest(Message):
    FIELDS = ()


class ListWorkersResponse(Message):
    FIELDS = (
        Field(1, "workers", "message", message_type=WorkerInfo, repeated=True),
        Field(2, "total_workers", "int32"),
    )


class GetPSAddressRequest(Message):
    FIELDS = ()


class GetPSAddressResponse(Message):
    """Field 3 is a framework extension: the FULL list of parameter-server
    shard addresses ("host:port", shard index = list index) when the store
    is partitioned across several PS processes.  Reference peers skip it
    per proto3 unknown-field rules and use fields 1/2 (shard 0)."""
    FIELDS = (
        Field(1, "address", "string"),
        Field(2, "port", "int32"),
        Field(3, "shards", "string", repeated=True),
    )


# --------------------------------------------------------------------------
# gRPC method tables (service and method names must match the reference IDL
# for wire-level interop: /parameter_server.ParameterServer/<M>,
# /coordinator.Coordinator/<M>)
# --------------------------------------------------------------------------

PARAMETER_SERVER_SERVICE = "parameter_server.ParameterServer"
COORDINATOR_SERVICE = "coordinator.Coordinator"

PARAMETER_SERVER_METHODS = {
    "ReceiveGradients": (GradientUpdate, PushResponse),
    "ServeParameters": (PullRequest, ParameterUpdate),
    "CheckSyncStatus": (SyncStatusRequest, SyncStatusResponse),
    "SaveCheckpoint": (SaveCheckpointRequest, SaveCheckpointResponse),
    "LoadCheckpoint": (LoadCheckpointRequest, LoadCheckpointResponse),
}

# Streaming data-plane extension (rpc/data_plane.py): the same push/pull
# payloads as a stream of chunk messages instead of one monolithic unary
# message.  Kept OUT of PARAMETER_SERVER_METHODS, whose method set is the
# reference IDL's (reference proto/parameter_server.proto:5-11) — these are
# extra method names on the same service that a reference peer simply never
# calls, and PSClient falls back to the unary RPCs when a reference server
# answers UNIMPLEMENTED.
PARAMETER_SERVER_STREAM_METHODS = {
    "PushGradientsStream": (GradientUpdate, PushResponse, "stream_unary"),
    "ServeParametersStream": (PullRequest, ParameterUpdate, "unary_stream"),
    # Fused data plane: client streams gradient chunks; the server applies
    # them, waits on the aggregation barrier (condition variable, no
    # polling), then streams the fresh parameter chunks back on the SAME
    # call — push + M sync polls + pull collapse into one RPC round.
    "PushPullStream": (GradientUpdate, PushPullResponse, "stream_stream"),
}

COORDINATOR_METHODS = {
    "RegisterWorker": (WorkerInfo, RegisterResponse),
    "Heartbeat": (HeartbeatRequest, HeartbeatResponse),
    "ListWorkers": (ListWorkersRequest, ListWorkersResponse),
    "GetParameterServerAddress": (GetPSAddressRequest, GetPSAddressResponse),
}


class ClusterMetricsRequest(Message):
    FIELDS = ()


class ClusterMetricsResponse(Message):
    """JSON rollup of the coordinator's per-worker metric snapshots
    (obs/export.ClusterAggregator.rollup)."""
    FIELDS = (Field(1, "rollup_json", "string"),)


# Observability extension (obs/export.py): the cluster metrics rollup as
# an extra method name on the coordinator service.  Kept OUT of
# COORDINATOR_METHODS (the reference IDL's method set, which interop tests
# pin); a reference client simply never calls it, and `pst-status
# --metrics` degrades gracefully against a reference coordinator
# (UNIMPLEMENTED).
COORDINATOR_EXT_METHODS = {
    "GetClusterMetrics": (ClusterMetricsRequest, ClusterMetricsResponse),
}
