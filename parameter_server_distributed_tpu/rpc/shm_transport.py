"""Shared-memory same-host transport for the fused data plane (ISSUE 6).

When a worker and its PS run on the same machine, the fused
``PushPullStream`` round still crosses the loopback TCP stack: every
chunk is HTTP/2-framed, copied into the kernel, copied back out, and
ACKed.  This module replaces that leg with two single-producer/
single-consumer byte rings in ``multiprocessing.shared_memory`` segments
— the SAME wire bytes (encoded ``GradientUpdate`` request frames one
way, ``PushPullResponse`` frames the other), so the codec, the message
schemas, and every aggregation semantic are untouched; only the
transport under them changes.

Negotiation (``NegotiateShm``) is an extension RPC on the parameter-
server service.  Its messages live HERE, not in ``rpc/messages.py``:
the wire-compat manifest pins the reference contract and must not
change — a reference peer simply never calls this method and answers
UNIMPLEMENTED, which the client treats exactly like the PR-2 stream
fallbacks: a PERMANENT per-connection downgrade to TCP.  The handshake
only succeeds when both ends report the same ``host_id`` (hostname +
kernel boot id — two containers that share a boot id but not /dev/shm
fail at segment attach and downgrade the same way) and the server can
actually create segments (/dev/shm unavailable => refused => TCP).

Ring protocol ("small doorbell"): each direction is a byte ring with two
u64 cursors in the segment header — ``tail`` (bytes ever written, owned
by the producer) and ``head`` (bytes ever read, owned by the consumer) —
plus a u32 ``closed`` latch either side may set.  A frame is a u32
length prefix followed by payload bytes, wrapped modulo the ring
capacity; frames larger than the ring stream through it in pieces,
published in ~1 MB blocks so the consumer's copy-out overlaps the
producer's copy-in.  The DOORBELL is a 1-byte nudge on a per-connection
AF_UNIX socket (abstract namespace — no filesystem litter): after
advancing a cursor the mover rings it, and a waiter parks in
``select`` — a real kernel wakeup, which matters twice: polling sleeps
have ~1 ms granularity on HZ-bound kernels, and in-process (tests,
colocated bench) a spinning waiter convoys the peer's copies under the
GIL.  Cursor updates are single aligned 8-byte stores — atomic on every
platform CPython runs on — and each cursor has exactly one writer; the
socket carries no data, only wakeups, so a lost/skipped doorbell is a
latency blip, never a correctness problem (waits recheck the cursors).

Env knobs: ``PSDT_SHM`` (default on; 0 disables both ends),
``PSDT_SHM_RING_BYTES`` (per-direction ring capacity, default 32 MB —
frames larger than the ring stream through it).
Observability: ``rpc.shm.bytes`` counts payload bytes moved through
rings by this process; ``rpc.shm.fallback`` counts downgrades to TCP
(refused negotiation, attach failure, or a mid-flight transport error).
"""

from __future__ import annotations

import ctypes
import logging
import os
import socket
import struct
import threading
import time
import uuid
from typing import Callable, Iterator

import numpy as np

from .. import native
from ..analysis.lock_order import checked_lock
from ..obs import flight
from ..obs import stats as obs_stats
from ..obs import trace as obs_trace
from .wire import Field, Message

log = logging.getLogger("pst.shm")

ENV_FLAG = "PSDT_SHM"
ENV_RING_BYTES = "PSDT_SHM_RING_BYTES"
# Frames larger than the ring stream through it in blocks, so the ring
# only needs to be big enough to decouple the two sides — and every ring
# page is touched at negotiation (see _pretouch), so smaller also means
# a shorter warm-up.
DEFAULT_RING_BYTES = 32 << 20

# Segment header layout (64-byte cache line):
#   0  u64 tail   — bytes ever written (producer-owned cursor)
#   8  u64 head   — bytes ever read   (consumer-owned cursor)
#   16 u32 closed — either side latches 1 to tear the connection down
_HEADER = 64
_OFF_TAIL = 0
_OFF_HEAD = 8
_OFF_CLOSED = 16

_obs_bytes = obs_stats.counter("rpc.shm.bytes")
_obs_fallback = obs_stats.counter("rpc.shm.fallback")


def enabled() -> bool:
    return os.environ.get(ENV_FLAG, "1") not in ("0", "false", "off")


def ring_bytes() -> int:
    return int(os.environ.get(ENV_RING_BYTES, str(DEFAULT_RING_BYTES)))


def host_id() -> str:
    """Same-host identity: hostname + kernel boot id.  The boot id guards
    against same-named hosts across a fleet; /dev/shm isolation between
    containers sharing a boot id is caught later, at segment attach."""
    boot = ""
    try:
        with open("/proc/sys/kernel/random/boot_id",
                  encoding="ascii") as fh:
            boot = fh.read().strip()
    except OSError:
        boot = "no-boot-id"
    return f"{socket.gethostname()}/{boot}"


class ShmTransportError(RuntimeError):
    """Any shared-memory transport failure.  The catcher downgrades the
    connection to TCP permanently (rpc/data_plane.py PSClient)."""


# --------------------------------------------------------------------------
# Negotiation messages — deliberately NOT in rpc/messages.py: the analyzer's
# wire manifest pins the reference contract, and this extension must leave
# it untouched.  A reference server answers the method with UNIMPLEMENTED.
# --------------------------------------------------------------------------

class ShmNegotiateRequest(Message):
    FIELDS = (
        Field(1, "host_id", "string"),
        Field(2, "worker_id", "int32"),
        Field(3, "ring_bytes", "int64"),
    )


class ShmNegotiateResponse(Message):
    """``accepted`` False carries the refusal reason in ``message`` (host
    mismatch, shm unavailable, disabled) — the client downgrades to TCP
    for the connection's lifetime either way.  ``doorbell`` is the
    abstract AF_UNIX address of the connection's doorbell socket."""
    FIELDS = (
        Field(1, "accepted", "bool"),
        Field(2, "message", "string"),
        Field(3, "c2s_name", "string"),
        Field(4, "s2c_name", "string"),
        Field(5, "ring_bytes", "int64"),
        Field(6, "host_id", "string"),
        Field(7, "doorbell", "string"),
    )


# Extension method table, bound alongside the reference + stream methods on
# the same gRPC service (server/ps_service.py).
SHM_METHODS = {
    "NegotiateShm": (ShmNegotiateRequest, ShmNegotiateResponse),
}


# Serializes the attach-side resource-tracker suppression below (the
# monkeypatch window must not race a concurrent attach).
_attach_lock = threading.Lock()


class _Doorbell:
    """1-byte wakeups over the connection's AF_UNIX socket.  Purely an
    optimization channel: the authoritative state is the ring cursors,
    so sends are fire-and-forget (a full socket buffer means the peer
    already has wakeups pending) and a waiter treats any readable byte —
    or a timeout — as "recheck the cursors"."""

    __slots__ = ("_sock",)

    def __init__(self, sock: socket.socket):
        sock.setblocking(False)
        self._sock = sock

    def ring(self) -> None:
        try:
            self._sock.send(b"\x01")
        except (BlockingIOError, OSError):  # buffer full / torn down
            pass

    def wait(self, timeout: float) -> None:
        import select
        try:
            readable, _, _ = select.select([self._sock], [], [], timeout)
            if readable:
                data = self._sock.recv(4096)
                if not data:
                    raise ShmTransportError("doorbell socket closed by peer")
        except BlockingIOError:  # drained by a concurrent recheck
            pass
        except OSError as exc:
            raise ShmTransportError(f"doorbell socket failed: {exc}") \
                from exc

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:  # already closed
            pass


def _doorbell_listener() -> tuple[socket.socket, str]:
    """Listening doorbell socket + its wire-encodable address ("@name"
    for the Linux abstract namespace, a filesystem path elsewhere)."""
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    name = f"psdt-db-{os.getpid()}-{uuid.uuid4().hex[:8]}"
    try:
        sock.bind("\0" + name)
        addr = "@" + name
    except OSError:
        import tempfile
        path = os.path.join(tempfile.gettempdir(), name)
        sock.bind(path)
        addr = path
    sock.listen(1)
    return sock, addr


def _doorbell_connect(addr: str, timeout: float = 10.0) -> socket.socket:
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(timeout)
    sock.connect("\0" + addr[1:] if addr.startswith("@") else addr)
    return sock


class ShmRing:
    """One direction of a connection: SPSC byte ring over a shared-memory
    segment.  Exactly one producer process/thread calls the ``write*``
    methods and one consumer the ``read*`` methods; the cursors make the
    hand-off safe without any cross-process lock.  ``doorbell`` (shared
    by both of a connection's rings at each endpoint) turns waits into
    kernel sleeps; without one — unit tests — waits degrade to timed
    polling."""

    def __init__(self, shm, capacity: int,
                 doorbell: _Doorbell | None = None):
        self._shm = shm
        self.capacity = capacity
        self._buf = shm.buf
        self.doorbell = doorbell
        # Bulk copies go through the native GIL-FREE memcpy when the lib
        # is available (native.copy_fn): a colocated producer/consumer
        # pair then overlaps its copies, where memoryview assignment
        # (the no-compiler fallback) convoys them under the GIL one
        # switch-interval at a time.  The raw base address stays valid
        # for the mmap's lifetime; teardown orders close() (latch, makes
        # waiters raise) before the unmap, and the server side refuses
        # to unmap under a still-running connection thread.
        self._copy = native.copy_fn()
        if self._copy is not None:
            carr = (ctypes.c_ubyte * len(shm.buf)).from_buffer(shm.buf)
            self._base = ctypes.addressof(carr)
            del carr  # export released; the address outlives it
        else:
            self._base = 0

    # ------------------------------------------------------------- cursors
    def _tail(self) -> int:
        return struct.unpack_from("<Q", self._buf, _OFF_TAIL)[0]

    def _head(self) -> int:
        return struct.unpack_from("<Q", self._buf, _OFF_HEAD)[0]

    def _set_tail(self, v: int) -> None:
        struct.pack_into("<Q", self._buf, _OFF_TAIL, v)

    def _set_head(self, v: int) -> None:
        struct.pack_into("<Q", self._buf, _OFF_HEAD, v)

    @property
    def closed(self) -> bool:
        try:
            return struct.unpack_from("<I", self._buf, _OFF_CLOSED)[0] != 0
        except (ValueError, TypeError):  # memoryview released (teardown)
            return True

    def close(self) -> None:
        try:
            struct.pack_into("<I", self._buf, _OFF_CLOSED, 1)
        except (ValueError, TypeError):  # segment already unmapped: the
            pass  # release latch beat this closer — nothing left to latch

    def invalidate(self) -> None:
        """Drop the native raw-address fast path BEFORE the segment
        unmaps (ISSUE 8 shm-flake fix): a copy racing the unmap then
        takes the memoryview path, whose released-buffer ``ValueError``
        is caught and surfaced as :class:`ShmTransportError` — a clean
        downgrade instead of a SIGSEGV at a stale ``_base``."""
        self._base = 0  # zeroed FIRST: a racing block re-reads (base,
        self._copy = None  # copy) and falls back once either is gone

    # ------------------------------------------------------------ doorbell
    def _wait(self, ready: Callable[[], int], deadline: float,
              what: str) -> int:
        """Park until ``ready()`` returns non-zero (bytes available /
        free).  One immediate probe, then escalating micro-sleeps — the
        "doorbell" is the peer's cursor store becoming visible.  NO hot
        spinning: under the GIL a spinning waiter convoys the peer's copy
        loop (each hand-off costs a full switch interval), so yielding
        immediately is strictly faster in-process and costs at most one
        ~20 us sleep cross-process."""
        while True:
            n = ready()
            if n:
                return n
            if self.closed:
                raise ShmTransportError(f"shm ring closed while {what}")
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise ShmTransportError(f"shm ring timeout while {what}")
            if self.doorbell is not None:
                # kernel sleep until the peer rings (capped so a closed
                # latch set without a ring is still noticed promptly)
                self.doorbell.wait(min(remaining, 0.05))
            else:
                time.sleep(min(remaining, 200e-6))

    # Copies are published in blocks of this size: the consumer starts
    # draining block 0 while the producer copies block 1, so a large frame
    # moves at ~memcpy speed instead of write-then-read serial (and no
    # single GIL-holding copy starves the peer for the whole frame).
    _BLOCK = 1 << 20

    # ------------------------------------------------------------- produce
    def _copy_in(self, pos: int, view, src, src_off: int, n: int) -> None:
        # re-read the native fast path per block: invalidate() may have
        # dropped it mid-frame (teardown racing a producer), and the
        # memoryview fallback fails CLEANLY on a released segment
        base, copy = self._base, self._copy
        if src is not None and copy is not None and base:
            copy(base + _HEADER + pos, src.ctypes.data + src_off, n)
        else:
            self._buf[_HEADER + pos:_HEADER + pos + n] = \
                view[src_off:src_off + n]

    def _write_bytes(self, data, deadline: float) -> None:
        view = memoryview(data)
        total = view.nbytes
        # the local ndarray keeps the source buffer alive for the call
        src = np.frombuffer(view, np.uint8) if self._copy is not None \
            else None
        cap = self.capacity
        tail = self._tail()
        sent = 0
        while sent < total:
            free = self._wait(
                lambda: cap - (tail - self._head()), deadline, "writing")
            n = min(free, total - sent, self._BLOCK)
            pos = tail % cap
            first = min(n, cap - pos)
            self._copy_in(pos, view, src, sent, first)
            if n > first:
                self._copy_in(0, view, src, sent + first, n - first)
            tail += n
            self._set_tail(tail)
            if self.doorbell is not None:
                self.doorbell.ring()
            sent += n

    # End-of-stream sentinel in the length slot.  Deliberately NOT length
    # zero: a fully-default GradientUpdate legally encodes to b"" under
    # proto3 default elision (the sharded-topology empty barrier
    # contribution at worker 0 / iteration 0), so zero-length DATA frames
    # must round-trip.
    _END = 0xFFFFFFFF

    def write_frame(self, payload, deadline: float) -> None:
        """One length-prefixed frame (zero-length payloads are legal).
        Frames larger than the ring stream through it — the consumer
        drains while the producer refills."""
        try:
            self._write_bytes(struct.pack("<I", len(payload)), deadline)
            if len(payload):
                self._write_bytes(payload, deadline)
        except ValueError as exc:  # memoryview released under us
            raise ShmTransportError(f"shm segment released: {exc}") from exc
        _obs_bytes.add(4 + len(payload))

    def write_end(self, deadline: float) -> None:
        """End-of-stream marker for one request/response group."""
        try:
            self._write_bytes(struct.pack("<I", self._END), deadline)
        except ValueError as exc:
            raise ShmTransportError(f"shm segment released: {exc}") from exc
        _obs_bytes.add(4)

    # ------------------------------------------------------------- consume
    def _copy_out(self, out: bytearray, dst, dst_off: int, pos: int,
                  n: int) -> None:
        base, copy = self._base, self._copy  # see _copy_in
        if dst is not None and copy is not None and base:
            copy(dst.ctypes.data + dst_off, base + _HEADER + pos, n)
        else:
            out[dst_off:dst_off + n] = self._buf[_HEADER + pos:
                                                 _HEADER + pos + n]

    def _read_bytes(self, n: int, deadline: float) -> bytearray:
        out = bytearray(n)
        dst = np.frombuffer(out, np.uint8) if self._copy is not None \
            else None
        done = 0
        cap = self.capacity
        head = self._head()
        while done < n:
            avail = self._wait(
                lambda: self._tail() - head, deadline, "reading")
            take = min(avail, n - done, self._BLOCK)
            pos = head % cap
            first = min(take, cap - pos)
            self._copy_out(out, dst, done, pos, first)
            if take > first:
                self._copy_out(out, dst, done + first, 0, take - first)
            head += take
            self._set_head(head)
            if self.doorbell is not None:
                self.doorbell.ring()
            done += take
        return out

    def read_frame(self, deadline: float) -> bytes | None:
        """The next frame's payload, or None at an end-of-stream marker."""
        try:
            (length,) = struct.unpack("<I", self._read_bytes(4, deadline))
            if length == self._END:
                _obs_bytes.add(4)
                return None
            payload = bytes(self._read_bytes(length, deadline)) if length \
                else b""
        except ValueError as exc:  # memoryview released under us
            raise ShmTransportError(f"shm segment released: {exc}") from exc
        _obs_bytes.add(4 + length)
        return payload


def _pretouch(shm) -> None:
    """Fault every page of the mapping in now (one store per 4 KB page):
    first-touch page faults during the first ring lap otherwise dominate
    the first few fused rounds."""
    view = np.frombuffer(shm.buf, np.uint8)
    view[_HEADER::4096] |= 0  # read-modify-write: faults without clobbering


def _create_segment(name: str, size: int):
    from multiprocessing import shared_memory
    with _attach_lock:
        # under the same lock as the attach-side tracker suppression: a
        # concurrent attach must not swallow this create's registration
        shm = shared_memory.SharedMemory(name=name, create=True, size=size)
    # zero the header so cursors/closed start clean (POSIX shm is
    # zero-filled, but be explicit — the protocol depends on it)
    shm.buf[:_HEADER] = bytes(_HEADER)
    _pretouch(shm)
    return shm


def _attach_segment(name: str):
    """Attach to a server-owned segment WITHOUT registering it with this
    process's resource tracker: the server is the owner and unlinks it; a
    client-side registration would double-unlink at exit (and, in the
    same-process test topology, fight the server's own registration).
    Python 3.13 grew ``track=False`` for exactly this; earlier versions
    need the documented workaround of suppressing ``register`` around the
    attach (bpo-38119)."""
    from multiprocessing import shared_memory
    try:
        shm = shared_memory.SharedMemory(name=name, create=False,
                                         track=False)
        _pretouch(shm)
        return shm
    except TypeError:  # Python < 3.13: no track kwarg
        pass
    from multiprocessing import resource_tracker
    with _attach_lock:
        orig = resource_tracker.register
        resource_tracker.register = lambda *a, **kw: None
        try:
            shm = shared_memory.SharedMemory(name=name, create=False)
        finally:
            resource_tracker.register = orig
    _pretouch(shm)
    return shm


class ShmClientConnection:
    """Worker-side endpoint of one negotiated connection: writes request
    frames to the c2s ring, reads response frames from the s2c ring.
    ``_lock`` serializes whole fused rounds — the rings are SPSC, so two
    concurrent pushes on one connection would interleave frames."""

    def __init__(self, c2s_name: str, s2c_name: str, capacity: int,
                 doorbell_addr: str = ""):
        self._c2s_shm = _attach_segment(c2s_name)
        self._s2c_shm = _attach_segment(s2c_name)
        self._doorbell = (_Doorbell(_doorbell_connect(doorbell_addr))
                          if doorbell_addr else None)
        self.c2s = ShmRing(self._c2s_shm, capacity, self._doorbell)
        self.s2c = ShmRing(self._s2c_shm, capacity, self._doorbell)
        # Serializes one fused round end to end; the ring waits under it
        # are the lock's purpose (BLOCKING_ALLOWED, analysis/lock_order.py)
        self._lock = checked_lock("ShmClientConnection._lock")

    def round_trip(self, frames: Iterator[bytes],
                   timeout: float | None) -> Iterator[bytes]:
        """One request/response exchange: stream the request frames out,
        then collect response frames until the server's end marker.  The
        response is fully drained inside the lock before yielding — a
        half-consumed iterator must not hold the connection hostage, and
        the buffered encoded frames are the same bytes the server's
        encode-once cache already holds per version, so peak memory
        matches the TCP fan-out's server side (the cost is losing the
        per-chunk decode ⊕ transport overlap the gRPC path streams;
        acceptable against the ~2x round-time win on loopback)."""
        deadline = time.monotonic() + (timeout if timeout else 3600.0)
        with self._lock:
            try:
                for frame in frames:
                    self.c2s.write_frame(frame, deadline)
                self.c2s.write_end(deadline)
                out: list[bytes] = []
                while True:
                    frame = self.s2c.read_frame(deadline)
                    if frame is None:
                        break
                    out.append(frame)
            except ShmTransportError:
                raise
            except BaseException:
                # the FRAME SOURCE raised mid-round (lazy D2H fetch,
                # encode validation): the stream is desynced — the server
                # is parked mid-round and would fold the NEXT round's
                # frames into this one.  Latch the rings closed so the
                # server thread exits (and is reaped) and the next
                # attempt on this connection downgrades to TCP; the
                # original error still propagates like the gRPC path's.
                for ring in (self.c2s, self.s2c):
                    try:
                        ring.close()
                    except (ValueError, OSError):
                        pass
                raise
        return iter(out)

    def close(self) -> None:
        # taking the round lock first means an in-flight fused round
        # finishes (or times out) before the segments unmap — raw-address
        # copies must never race the unmap
        with self._lock:
            for ring in (self.c2s, self.s2c):
                try:
                    ring.close()
                except (ValueError, OSError):  # segment already torn down
                    pass
            if self._doorbell is not None:
                self._doorbell.close()
            for shm in (self._c2s_shm, self._s2c_shm):
                try:
                    shm.close()
                except OSError:  # noqa: BLE001 — double-close at teardown
                    pass


class _ServerConnection:
    """PS-side endpoint: a dedicated thread drains request frames, feeds
    them through the fused handler, and streams the response frames
    back.  One thread per same-host worker — they park on the barrier
    condition variable exactly like gRPC handler threads do."""

    def __init__(self, index: int, handler: Callable, capacity: int,
                 on_exit: Callable[["_ServerConnection"], None]
                 | None = None):
        token = uuid.uuid4().hex[:8]
        self.index = index
        self._on_exit = on_exit
        # Exactly-once segment release (ISSUE 8: the PR-7 backup-crash
        # flake was a DOUBLE segment reap — the serve thread's exit reap
        # racing the shutdown path's unlink, second unmap pulling the
        # mapping out from under a native ring copy).  Every unmap now
        # routes through release_segments(), which latches.
        self._release_lock = checked_lock("_ServerConnection._release_lock")
        self._released = False
        self.c2s_name = f"psdt-{os.getpid()}-{index}-{token}-c2s"
        self.s2c_name = f"psdt-{os.getpid()}-{index}-{token}-s2c"
        self._listener, self.doorbell_addr = _doorbell_listener()
        self._c2s_shm = _create_segment(self.c2s_name,
                                        _HEADER + capacity)
        self._s2c_shm = _create_segment(self.s2c_name,
                                        _HEADER + capacity)
        self.c2s = ShmRing(self._c2s_shm, capacity)
        self.s2c = ShmRing(self._s2c_shm, capacity)
        self._doorbell: _Doorbell | None = None
        self._handler = handler
        self._thread = threading.Thread(
            target=self._serve_loop, daemon=True,
            name=f"shm-conn-{index}")
        self._thread.start()

    def _request_frames(self) -> Iterator[bytes]:
        """Frames of ONE request (until the client's end marker); empty
        frames are legal data (an all-default GradientUpdate)."""
        while True:
            frame = self.c2s.read_frame(time.monotonic() + 3600.0)
            if frame is None:
                return
            yield frame

    def _serve_loop(self) -> None:
        from . import messages as m
        try:
            self._listener.settimeout(60.0)
            sock, _ = self._listener.accept()
        except OSError:
            # client never connected its doorbell (died mid-negotiation,
            # or teardown closed the listener): the rings are unused
            self.close()
            if self._on_exit is not None:
                self._on_exit(self)
            return
        finally:
            try:
                self._listener.close()
            except OSError:
                pass
        self._doorbell = _Doorbell(sock)
        self.c2s.doorbell = self._doorbell
        self.s2c.doorbell = self._doorbell
        try:
            self._serve_rounds(m)
        finally:
            if self._on_exit is not None:
                # client gone (orderly close or crash-latched ring):
                # release this connection's segments NOW instead of at PS
                # shutdown — elastic worker churn must not accrete
                # 2x-ring-sized /dev/shm leaks per former worker
                self._on_exit(self)

    def _serve_rounds(self, m) -> None:
        while True:
            try:
                # park (uncapped) for the next round's first frame, then
                # decode chunks as they arrive so the handler's fold
                # overlaps the client's remaining writes
                first = self.c2s.read_frame(time.monotonic() + 2**31)
            except ShmTransportError:
                return  # closed / torn down
            try:
                if first is None:
                    continue  # stray end marker (client retry teardown)
                drained = [False]
                # a shm round IS a fused PushPullStream round: give it
                # the same server-side span (adopting the caller's trace
                # context off the chunks — the field-999 plumbing the
                # ring transport otherwise bypasses) and the same flight
                # start/end stamps as the gRPC handler path
                t0 = time.perf_counter()
                flight.record("rpc.srv.start", note="PushPull/shm")
                holder = obs_trace.SpanHolder("rpc/server/PushPullStream",
                                              transport="shm")

                def chunks() -> Iterator[m.Message]:
                    chunk = m.GradientUpdate.decode(first)
                    holder.adopt(getattr(chunk, "trace_context", b""))
                    yield chunk
                    for frame in self._request_frames():
                        chunk = m.GradientUpdate.decode(frame)
                        holder.adopt(getattr(chunk, "trace_context", b""))
                        yield chunk
                    drained[0] = True

                deadline = time.monotonic() + 3600.0
                try:
                    for resp in self._handler(chunks(), None):
                        self.s2c.write_frame(resp.encode(), deadline)
                finally:
                    holder.finish()
                    flight.record(
                        "rpc.srv.end",
                        a=int(1e6 * (time.perf_counter() - t0)),
                        note="PushPull/shm")
                if not drained[0]:
                    # handler returned early (e.g. the empty-store fused
                    # refusal never reads the gradient chunks): consume the
                    # round's remaining frames so the NEXT round's first
                    # frame is really a first frame — and so a client
                    # blocked writing a ring-sized push gets unstuck
                    for _ in self._request_frames():
                        pass
                self.s2c.write_end(deadline)
            except ShmTransportError:
                return
            except Exception:  # noqa: BLE001 — keep serving other rounds
                log.exception("shm connection handler failed; closing")
                self.close()
                return

    def close(self) -> None:
        for ring in (self.c2s, self.s2c):
            try:
                ring.close()
            except (ValueError, OSError):
                pass
        for sock in (self._doorbell, self._listener):
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass

    def release_segments(self, unmap: bool = True) -> bool:
        """Exactly-once segment release — THE fix for the PR-7 backup
        crash flake.  Before the latch, two paths could both reach the
        unmap for one connection (the serve thread's exit reap and the
        shutdown path's unlink, under post-failover worker churn), and
        the loser unmapped a segment whose ring a native copy could still
        be dereferencing through its raw base pointer: SIGSEGV in the
        backup PS (docs/observability.md has the decoded flight-ring
        evidence).  Returns False on the duplicate call (recorded as
        ``shm.reap.dup`` — the flake's witness event), True when this
        call performed the release.  ``unmap=False`` unlinks only (the
        deferred path when the serve thread cannot be joined)."""
        with self._release_lock:
            if self._released:
                flight.record("shm.reap.dup", a=self.index)
                return False
            self._released = True
        flight.record("shm.reap", a=self.index, b=1 if unmap else 0)
        # drop the raw-address fast path BEFORE any unmap: a racing
        # block copy falls back to the memoryview, which fails cleanly
        for ring in (self.c2s, self.s2c):
            ring.invalidate()
        for shm in (self._c2s_shm, self._s2c_shm):
            try:
                if unmap:
                    shm.close()
                shm.unlink()
            except (OSError, FileNotFoundError):  # already gone
                pass
        return True

    def unlink(self) -> None:
        self.close()
        self._thread.join(timeout=2.0)
        if self._thread.is_alive():
            # still parked inside the handler (e.g. a barrier wait):
            # unmapping under it would turn a slow shutdown into a raw-
            # address crash — leave the segments mapped (daemon thread +
            # resource tracker clean up at process exit) and only unlink
            # the names so no new attach can find them
            log.warning("shm connection thread still running at teardown; "
                        "deferring segment unmap")
            self.release_segments(unmap=False)
            return
        self.release_segments()


class ShmServer:
    """PS-side registry: answers ``NegotiateShm`` and owns the per-
    connection segments/threads.  ``handler`` is the fused stream handler
    (``ParameterServerService.PushPullStream`` — request-chunk iterator
    in, response iterator out)."""

    def __init__(self, handler: Callable,
                 capacity: int | None = None):
        self._handler = handler
        self._capacity = capacity if capacity is not None else ring_bytes()
        self._host_id = host_id()
        # leaf: held only around the connection-registry dict ops
        self._lock = checked_lock("ShmServer._lock")
        self._conns: list[_ServerConnection] = []
        self._next_index = 0
        self._closed = False

    def _reap(self, conn: "_ServerConnection") -> None:
        """Called FROM a connection's serving thread as it exits (client
        closed, crashed, or never finished the handshake): drop it from
        the registry and release its segments immediately.  The registry
        removal under the lock makes reap-vs-shutdown exactly-once; the
        unmap is safe because the exiting serve thread is the segments'
        last user."""
        with self._lock:
            if conn not in self._conns:
                return  # shutdown path already owns it
            self._conns.remove(conn)
        conn.close()
        # exactly-once via the connection's release latch: the registry
        # check above already dedups reap-vs-shutdown, but the latch also
        # covers the paths that bypass the registry (a connection that
        # never finished negotiation racing its own accept-timeout reap —
        # the PR-7 flake's double-reap window)
        conn.release_segments()
        log.info("shm connection reaped (client disconnected)")

    def _refuse(self, why: str) -> ShmNegotiateResponse:
        log.info("shm negotiation refused: %s", why)
        flight.record("shm.refuse", note=why)
        return ShmNegotiateResponse(accepted=False, message=why,
                                    host_id=self._host_id)

    def negotiate(self, request: ShmNegotiateRequest) -> ShmNegotiateResponse:
        if not enabled():
            return self._refuse("shm transport disabled (PSDT_SHM=0)")
        if request.host_id != self._host_id:
            return self._refuse(
                f"host mismatch: client {request.host_id!r} vs server "
                f"{self._host_id!r}")
        capacity = self._capacity
        if request.ring_bytes:
            capacity = min(capacity, int(request.ring_bytes))
        with self._lock:
            if self._closed:
                return self._refuse("server shutting down")
            index = self._next_index
            self._next_index += 1
        # segment creation + page pretouch + doorbell listen run OUTSIDE
        # the lock (tens of ms of I/O — the lock's contract is registry
        # dict ops only, and N workers negotiating at startup must not
        # serialize behind each other's page-fault storms)
        try:
            conn = _ServerConnection(index, self._handler, capacity,
                                     on_exit=self._reap)
        except (OSError, ValueError, ImportError) as exc:
            # /dev/shm unavailable, exhausted, or shared_memory
            # missing: refuse — the client stays on TCP
            return self._refuse(f"shared memory unavailable: {exc}")
        with self._lock:
            registered = not self._closed
            if registered:
                self._conns.append(conn)
        if not registered:  # shutdown raced the construction
            conn.unlink()
            return self._refuse("server shutting down")
        log.info("shm connection %d negotiated (worker %d, ring %d MB x2)",
                 index, request.worker_id, capacity >> 20)
        flight.record("shm.negotiate", worker=request.worker_id, a=index,
                      b=capacity)
        return ShmNegotiateResponse(
            accepted=True, message="ok", c2s_name=conn.c2s_name,
            s2c_name=conn.s2c_name, ring_bytes=capacity,
            host_id=self._host_id, doorbell=conn.doorbell_addr)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            conns, self._conns = list(self._conns), []
        for conn in conns:
            conn.unlink()
