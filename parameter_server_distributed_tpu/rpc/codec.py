"""The wire payload codec: one narrow interface, two implementations.

Every packed tensor payload on the wire (the ``Tensor.packed`` extension
field — rpc/messages.py) is produced and consumed through the
:class:`Codec` interface below:

- :class:`PythonCodec` — the pure-numpy reference implementation.  It is
  the BYTE-IDENTITY ORACLE: the payload layouts are defined by what this
  class emits, and every other implementation must match it bit for bit
  (fuzz-checked across dtypes/shapes in tests/test_codec.py).
- :class:`NativeCodec` — the C++ fast path (native/psdt_native.cpp, built
  by the existing ``native.lib()`` g++ machinery).  Encode/decode/
  quantize/dequantize run as single fused passes over zero-copy pointers
  into the caller's arrays and the encoder's preallocated message buffer;
  ctypes releases the GIL, so stripe-parallel encodes really occupy
  multiple cores.  Any operation the native library cannot take falls
  back to the inherited numpy path per call — never a different answer,
  at worst a slower one.

Selection is per-process: :func:`active_codec` resolves to the native
codec whenever ``native.lib()`` is available and enabled (``PSDT_NATIVE=0``
or ``native.set_enabled(False)`` forces the Python path — the bench A/B
knob).  The resolved choice is exported as the ``rpc.codec.native`` gauge.

Payload layouts (little-endian, pinned by the Python oracle):

- ``WIRE_RAW_F32``:  n * f32
- ``WIRE_BF16``:     n * bf16 (round-to-nearest-even)
- ``WIRE_INT8``:     f32 max-abs scale | n * int8
- ``WIRE_TOPK``:     u32 k | k * u32 ascending indices | k * bf16 values

Top-k selection is part of the codec contract: elements with |v| strictly
above the k-th largest |v|, threshold ties filled in ascending index
order (:func:`topk_indices`) — deterministic, so native and Python emit
identical bytes even on tied inputs.
"""

from __future__ import annotations

import numpy as np

from .. import native
from ..obs import flight
from ..obs import stats as obs_stats

# Wire encodings for Tensor payloads.  WIRE_F32 is the reference encoding
# (packed `repeated float`, field 3) and never reaches the codec; the
# packed encodings are a framework extension carried in fields 5/6, which
# reference peers skip per proto3 unknown-field rules.
WIRE_F32 = 0       # repeated float field 3 (reference-compatible, default)
WIRE_RAW_F32 = 1   # raw little-endian float32 bytes in field 5
WIRE_BF16 = 2      # raw bfloat16 bytes in field 5 — half the payload
WIRE_INT8 = 3      # f32 max-abs scale + int8 bytes in field 5 — quarter
                   # the payload (EQuARX-style quantized transport; pair
                   # with error feedback for gradients — worker/worker.py)
WIRE_TOPK = 4      # top-k sparsified: u32 k | k*u32 indices | k*bf16
                   # values in field 5 (Deep-Gradient-Compression-style
                   # transport; pair with error feedback so unsent mass
                   # is carried, not dropped — worker/worker.py)

# CLI/config name -> wire dtype.  Single definition; rpc/messages.py
# re-exports it (the analyzer manifest pins its VALUES through there).
WIRE_DTYPE_NAMES = {"f32": WIRE_F32, "raw": WIRE_RAW_F32, "bf16": WIRE_BF16,
                    "int8": WIRE_INT8, "topk": WIRE_TOPK}

# The packed encodings the codec handles (everything but repeated-float).
PACKED_WIRE_DTYPES = (WIRE_RAW_F32, WIRE_BF16, WIRE_INT8, WIRE_TOPK)

TOPK_DEFAULT_DENSITY = 0.01  # fraction of entries a topk tensor keeps


_BF16 = None


def bf16_dtype():
    global _BF16
    if _BF16 is None:
        import ml_dtypes  # ships with jax
        _BF16 = ml_dtypes.bfloat16
    return _BF16


def topk_k(size: int, density: float) -> int:
    """Kept-entry count for a WIRE_TOPK payload of ``size`` elements."""
    if not size:
        return 0
    return min(size, max(1, int(round(size * density))))


def payload_nbytes(wire_dtype: int, size: int, k: int = 0) -> int:
    """Exact payload byte count — known BEFORE any encode runs, which is
    what lets the two-pass exactly-sized encoder (wire.py) budget packed
    payloads lazily."""
    if wire_dtype == WIRE_RAW_F32:
        return 4 * size
    if wire_dtype == WIRE_BF16:
        return 2 * size
    if wire_dtype == WIRE_INT8:
        return 4 + size
    if wire_dtype == WIRE_TOPK:
        return 4 + 6 * k
    raise ValueError(f"not a packed wire dtype: {wire_dtype}")


def topk_indices(flat: np.ndarray, k: int) -> np.ndarray:
    """Deterministic top-k-|value| selection (ascending u32 indices).

    Threshold = the k-th largest |v| (``np.partition`` — value-defined, so
    every implementation agrees); everything strictly above it is kept,
    ties AT the threshold fill the remaining slots in ascending index
    order, and NaN entries (which compare false both ways but sort as
    the LARGEST values, numpy convention) fill any slots still left,
    ascending — so a diverging run's NaN gradients still encode exactly
    k entries instead of crashing the push.  The tie-break is part of
    the codec contract — it is what makes native and Python
    byte-identical on inputs like all-equal gradients, where an
    argpartition's arbitrary tie choice would diverge between
    implementations (and numpy versions)."""
    n = int(flat.size)
    if k >= n:
        return np.arange(n, dtype="<u4")
    ab = np.abs(flat)
    thr = np.partition(ab, n - k)[n - k]
    above = np.nonzero(ab > thr)[0]
    at = np.nonzero(ab == thr)[0][:k - above.size]
    short = k - above.size - at.size
    if short > 0:  # NaNs in the top-k range (possibly thr itself)
        at = np.concatenate([at, np.nonzero(np.isnan(ab))[0][:short]])
    return np.sort(np.concatenate([above, at])).astype("<u4")


class Codec:
    """Narrow payload codec interface: flat f32 array <-> packed payload
    bytes, for the packed WIRE_* encodings.

    ``pack_into`` writes the exact ``payload_nbytes`` payload of ``src``
    (flat contiguous float32) into the writable buffer ``dst`` — encode,
    quantize, and sparsify are all this one call, running straight into
    the outgoing message buffer (no intermediate copies).  ``unpack``
    inverts it: payload bytes -> flat f32 array (``total`` is the dense
    element count, needed by WIRE_TOPK's scatter).  Implementations MUST
    be byte-identical to :class:`PythonCodec` — it is the oracle.
    """

    name = "abstract"

    def pack_into(self, wire_dtype: int, src: np.ndarray, dst,
                  k: int = 0) -> None:
        raise NotImplementedError

    def unpack(self, wire_dtype: int, raw, total: int) -> np.ndarray:
        raise NotImplementedError


class PythonCodec(Codec):
    """Pure-numpy reference implementation — the byte-identity oracle and
    the always-available fallback (no compiler required)."""

    name = "python"

    def pack_into(self, wire_dtype: int, src: np.ndarray, dst,
                  k: int = 0) -> None:
        if wire_dtype == WIRE_RAW_F32:
            np.copyto(np.frombuffer(dst, dtype="<f4"), src,
                      casting="unsafe")
        elif wire_dtype == WIRE_BF16:
            # fused convert-and-store: the f32->bf16 cast writes straight
            # into the message buffer
            np.copyto(np.frombuffer(dst, dtype=bf16_dtype()), src,
                      casting="unsafe")
        elif wire_dtype == WIRE_INT8:
            out = np.frombuffer(dst, np.uint8)
            max_abs = float(np.max(np.abs(src))) if src.size else 0.0
            scale = max_abs / 127.0 if max_abs > 0 else 1.0
            out[:4] = np.frombuffer(np.float32(scale).tobytes(), np.uint8)
            q = np.clip(np.rint(src / np.float32(scale)),
                        -127, 127).astype(np.int8)
            out[4:] = q.view(np.uint8)
        elif wire_dtype == WIRE_TOPK:
            out = np.frombuffer(dst, np.uint8)
            out[:4] = np.frombuffer(np.uint32(k).tobytes(), np.uint8)
            if k:
                idx = topk_indices(src, k)
                vals = src[idx.astype(np.int64)].astype(bf16_dtype())
                out[4:4 + 4 * k] = idx.view(np.uint8)
                out[4 + 4 * k:] = vals.view(np.uint8)
        else:
            raise ValueError(f"not a packed wire dtype: {wire_dtype}")

    def unpack(self, wire_dtype: int, raw, total: int) -> np.ndarray:
        if wire_dtype == WIRE_BF16:
            return np.frombuffer(raw, dtype=bf16_dtype()).astype(np.float32)
        if wire_dtype == WIRE_RAW_F32:
            # zero-copy view; to_array() copies iff a writable array is
            # needed (the read-only view is the cost this codec avoids)
            return np.frombuffer(raw, dtype="<f4").astype(np.float32,
                                                          copy=False)
        if wire_dtype == WIRE_INT8:
            scale = np.frombuffer(raw, dtype="<f4", count=1)[0]
            return np.frombuffer(raw, dtype=np.int8,
                                 offset=4).astype(np.float32) * scale
        if wire_dtype == WIRE_TOPK:
            k = int(np.frombuffer(raw, dtype="<u4", count=1)[0])
            arr = np.zeros(total, np.float32)
            if k:
                idx = np.frombuffer(raw, dtype="<u4", offset=4, count=k)
                vals = np.frombuffer(raw, dtype=bf16_dtype(),
                                     offset=4 + 4 * k, count=k)
                arr[idx.astype(np.int64)] = vals.astype(np.float32)
            return arr
        raise ValueError(f"not a packed wire dtype: {wire_dtype}")


class NativeCodec(PythonCodec):
    """C++ fast path over zero-copy memoryviews (native/psdt_native.cpp).

    Each operation tries the native kernel and inherits the numpy path
    when it declines (library unavailable, unsuitable layout, or a
    malformed payload the Python path should reject loudly) — so a
    process that loses the native library mid-run degrades per call, not
    catastrophically."""

    name = "native"

    def pack_into(self, wire_dtype: int, src: np.ndarray, dst,
                  k: int = 0) -> None:
        if wire_dtype == WIRE_BF16:
            if native.pack_bf16_native(src, dst):
                return
        elif wire_dtype == WIRE_INT8:
            if native.quant_int8_native(src, dst):
                return
        elif wire_dtype == WIRE_TOPK:
            if native.topk_pack_native(src, k, dst):
                return
        # WIRE_RAW_F32 is a memcpy either way — numpy is already optimal
        super().pack_into(wire_dtype, src, dst, k)

    def unpack(self, wire_dtype: int, raw, total: int) -> np.ndarray:
        if wire_dtype == WIRE_BF16:
            out = np.empty(len(raw) // 2, np.float32)
            if native.unpack_bf16_native(raw, out):
                return out
        elif wire_dtype == WIRE_INT8:
            out = np.empty(max(0, len(raw) - 4), np.float32)
            if native.dequant_int8_native(raw, out):
                return out
        elif wire_dtype == WIRE_TOPK:
            out = np.empty(total, np.float32)
            if native.topk_unpack_native(raw, out):
                return out
        return super().unpack(wire_dtype, raw, total)


_PYTHON = PythonCodec()
_NATIVE = NativeCodec()
_gauge = obs_stats.gauge("rpc.codec.native")
_last: Codec | None = None


def active_codec() -> Codec:
    """The process-wide codec: native when the library is available and
    enabled (``PSDT_NATIVE``), the Python oracle otherwise.  Resolved per
    call — a few attribute reads — so ``native.set_enabled`` flips take
    effect immediately; the ``rpc.codec.native`` gauge records the
    resolved choice (1 = native)."""
    global _last
    codec: Codec = _NATIVE if native.lib() is not None else _PYTHON
    if codec is not _last:
        _gauge.set(1.0 if codec is _NATIVE else 0.0)
        # flight evidence: which codec this process resolved (and every
        # flip — a mid-run native failure downgrade is a postmortem clue)
        flight.record("codec.select", a=1 if codec is _NATIVE else 0)
        _last = codec
    return codec
