"""Minimal proto3 wire-format codec.

The reference ships two proto3 IDL files (proto/parameter_server.proto,
proto/coordinator.proto) compiled with protoc + grpc_cpp_plugin
(reference: CMakeLists.txt:87-113).  This framework stays wire-compatible
with those services without depending on protoc/grpc_tools gencode: messages
are declared in Python (`messages.py`) and encoded/decoded by this codec.

Only the subset of proto3 used by the reference schemas is implemented:

- varint scalar fields: int32, int64, bool, enum (wire type 0)
- fixed32 float fields (wire type 5)
- length-delimited: string, bytes, embedded messages, packed repeated
  scalars (wire type 2)
- repeated messages (one length-delimited record per element)
- packed repeated float / int32 — with the proto3 requirement that decoders
  accept both packed and unpacked encodings of repeated scalars
- proto3 default-value elision on encode; unknown-field skipping on decode

Packed `repeated float` payloads (the tensor data plane of the reference's
`Tensor` message — proto/parameter_server.proto:19-24) are moved as raw
little-endian buffers through numpy, i.e. memcpy-speed, with an optional
native C++ fast path (see native/).
"""

from __future__ import annotations

import ctypes
import struct
from typing import Any, Callable

import numpy as np

from . import codec as _codec

# Wire types
WT_VARINT = 0
WT_FIXED64 = 1
WT_LEN = 2
WT_FIXED32 = 5

_U64_MASK = (1 << 64) - 1

# bytes fields at or below this size are copied out of the RPC buffer at
# decode time (see the "bytes" branch in _decode_field); larger payloads
# (tensor data) stay zero-copy memoryviews into the caller's buffer.
_BYTES_COPY_THRESHOLD = 4096


def encode_varint(value: int) -> bytes:
    """Encode a non-negative (or two's-complement 64-bit wrapped) varint."""
    value &= _U64_MASK
    out = bytearray()
    while True:
        byte = value & 0x7F
        value >>= 7
        if value:
            out.append(byte | 0x80)
        else:
            out.append(byte)
            return bytes(out)


def decode_varint(buf: bytes, pos: int) -> tuple[int, int]:
    """Decode a varint at ``pos``; returns (value, new_pos)."""
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        byte = buf[pos]
        pos += 1
        result |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return result & _U64_MASK, pos
        shift += 7
        if shift >= 70:
            raise ValueError("varint too long")


def _signed32(value: int) -> int:
    """Interpret a decoded varint as int32 (two's complement, per proto3)."""
    value &= 0xFFFFFFFF
    if value >= 1 << 31:
        value -= 1 << 32
    return value


def _signed64(value: int) -> int:
    value &= _U64_MASK
    if value >= 1 << 63:
        value -= 1 << 64
    return value


def _tag(field_number: int, wire_type: int) -> bytes:
    return encode_varint((field_number << 3) | wire_type)


def _skip_field(buf: bytes, pos: int, wire_type: int) -> int:
    if wire_type == WT_VARINT:
        _, pos = decode_varint(buf, pos)
    elif wire_type == WT_FIXED64:
        pos += 8
    elif wire_type == WT_LEN:
        length, pos = decode_varint(buf, pos)
        pos += length
    elif wire_type == WT_FIXED32:
        pos += 4
    else:
        raise ValueError(f"unsupported wire type {wire_type}")
    if pos > len(buf):
        raise ValueError("truncated field")
    return pos


class Field:
    """Declarative spec for one proto3 field."""

    __slots__ = ("number", "name", "kind", "message_type", "repeated")

    def __init__(self, number: int, name: str, kind: str,
                 message_type: type | None = None, repeated: bool = False):
        self.number = number
        self.name = name
        self.kind = kind  # int32|int64|bool|enum|string|bytes|float|message
        self.message_type = message_type
        self.repeated = repeated


class Message:
    """Base class for declarative proto3 messages.

    Subclasses define ``FIELDS: tuple[Field, ...]`` and plain attributes.
    """

    FIELDS: tuple[Field, ...] = ()

    def __init__(self, **kwargs: Any):
        for f in self.FIELDS:
            setattr(self, f.name, kwargs.pop(f.name, _default_for(f)))
        if kwargs:
            raise TypeError(f"unknown fields for {type(self).__name__}: {sorted(kwargs)}")

    # -- encoding ---------------------------------------------------------
    def encode(self) -> bytes:
        """Two-pass encode: size everything, preallocate once, write in
        place.  Naive bytearray appending copies each nested tensor body
        ~3x (child buffer -> parent growth -> final bytes); at config-3
        scale (hundreds of MB per push) those copies dominate push/pull
        latency, so the encoder is exactly-sized instead."""
        writer = _Writer(self.encoded_size())
        self.encode_into(writer)
        return writer.getvalue()

    def encoded_size(self) -> int:
        return sum(_field_size(f, getattr(self, f.name))
                   for f in self.FIELDS)

    def encode_into(self, writer: "_Writer") -> None:
        for f in self.FIELDS:
            _encode_field(writer, f, getattr(self, f.name))

    # -- decoding ---------------------------------------------------------
    @classmethod
    def decode(cls, buf: bytes | memoryview):
        msg = cls()
        # memoryview input decodes zero-copy; nested messages and bytes
        # fields become views into the caller's buffer (which they keep
        # alive), so a 100MB+ gradient push is never re-sliced wholesale
        if not isinstance(buf, (bytes, memoryview)):
            buf = bytes(buf)
        by_number = cls._fields_by_number()
        pos = 0
        n = len(buf)
        while pos < n:
            key, pos = decode_varint(buf, pos)
            field_number = key >> 3
            wire_type = key & 0x7
            f = by_number.get(field_number)
            if f is None:
                pos = _skip_field(buf, pos, wire_type)
                continue
            pos = _decode_field(msg, buf, pos, f, wire_type)
        return msg

    _BY_NUMBER_CACHE: dict[type, dict[int, Field]] = {}

    @classmethod
    def _fields_by_number(cls) -> dict[int, Field]:
        cached = Message._BY_NUMBER_CACHE.get(cls)
        if cached is None:
            cached = {f.number: f for f in cls.FIELDS}
            Message._BY_NUMBER_CACHE[cls] = cached
        return cached

    # -- misc -------------------------------------------------------------
    def __repr__(self) -> str:
        parts = []
        for f in self.FIELDS:
            v = getattr(self, f.name)
            if isinstance(v, np.ndarray):
                v = f"<float32[{v.size}]>"
            parts.append(f"{f.name}={v!r}")
        return f"{type(self).__name__}({', '.join(parts)})"

    def __eq__(self, other: object) -> bool:
        if type(other) is not type(self):
            return NotImplemented
        for f in self.FIELDS:
            a, b = getattr(self, f.name), getattr(other, f.name)
            if f.kind == "float" and f.repeated:
                if not np.array_equal(np.asarray(a, np.float32), np.asarray(b, np.float32)):
                    return False
            elif a != b:
                return False
        return True


def _default_for(f: Field) -> Any:
    if f.repeated:
        return np.zeros((0,), np.float32) if f.kind == "float" else []
    return {
        "int32": 0, "int64": 0, "enum": 0, "bool": False,
        "string": "", "bytes": b"", "float": 0.0,
    }.get(f.kind) if f.kind != "message" else None


class ArrayPayload:
    """Lazy bytes-field payload: a flat float32 source array plus the
    packed WIRE_* encoding it should be sent as.  The encode — dtype cast,
    int8 quantization, or top-k sparsify+pack — happens directly into the
    outgoing message buffer at encode time (``_Writer.write_array``)
    through the active :class:`~.codec.Codec`: ONE fused pass instead of
    separate quantize + ``tobytes`` + buffer-write sweeps.  At config-3
    scale (GBs of tensor payload per push) those extra sweeps dominate
    encode latency, and routing them through the codec is what lets the
    native C++ path (``PSDT_NATIVE``) take over the byte work.

    Anything that needs the payload outside an encode (same-process
    ``to_array``, equality in tests) materializes via :meth:`tobytes`,
    which reproduces the exact bytes a wire round-trip would carry; the
    materialization is cached so a later encode replays it as a memcpy
    (e.g. the error-feedback residual path reads ``to_array`` before the
    push encodes — the quantize then runs once, not twice).
    """

    __slots__ = ("src", "wire_dtype", "k", "nbytes", "_cache")

    def __init__(self, src: np.ndarray, wire_dtype: int, k: int = 0) -> None:
        self.src = np.ascontiguousarray(src, np.float32).reshape(-1)
        self.wire_dtype = int(wire_dtype)
        self.k = int(k)
        self.nbytes = _codec.payload_nbytes(self.wire_dtype, self.src.size,
                                            self.k)
        self._cache: bytes | None = None

    def __len__(self) -> int:
        return self.nbytes

    def __bool__(self) -> bool:
        return self.nbytes > 0

    def pack_into(self, dst) -> None:
        """Write the exact payload bytes into the writable buffer ``dst``
        (length ``nbytes``) via the active codec."""
        if self._cache is not None:
            dst[:] = self._cache
        else:
            _codec.active_codec().pack_into(self.wire_dtype, self.src, dst,
                                            self.k)

    def tobytes(self) -> bytes:
        if self._cache is None:
            buf = bytearray(self.nbytes)
            _codec.active_codec().pack_into(self.wire_dtype, self.src,
                                            memoryview(buf), self.k)
            self._cache = bytes(buf)
        return self._cache

    def __eq__(self, other: object) -> bool:
        if isinstance(other, ArrayPayload):
            other = other.tobytes()
        if isinstance(other, (bytes, bytearray, memoryview)):
            return self.tobytes() == bytes(other)
        return NotImplemented


# Uninitialized-bytes allocation via the CPython C API: the encoder writes
# its output directly into the `bytes` object handed to gRPC (whose cython
# layer accepts nothing else), skipping both bytearray's zero-fill sweep
# and the final buffer->bytes copy.  Mutating the object is safe because it
# is unreachable by any other code until encode() returns it.
_pyapi = ctypes.pythonapi
_pyapi.PyBytes_FromStringAndSize.restype = ctypes.py_object
_pyapi.PyBytes_FromStringAndSize.argtypes = [ctypes.c_char_p, ctypes.c_ssize_t]
_pyapi.PyBytes_AsString.restype = ctypes.c_void_p
_pyapi.PyBytes_AsString.argtypes = [ctypes.py_object]


def _alloc_uninit_bytes(size: int) -> tuple[bytes, np.ndarray]:
    """Return (bytes_of_len_size, writable uint8 view into it)."""
    obj = _pyapi.PyBytes_FromStringAndSize(None, size)
    addr = _pyapi.PyBytes_AsString(obj)
    view = np.frombuffer((ctypes.c_ubyte * size).from_address(addr), np.uint8)
    return obj, view


class _Writer:
    """Exact-size in-place buffer writer (see Message.encode), backed by an
    uninitialized `bytes` object so ``getvalue()`` is zero-copy (gRPC's
    serializer contract requires `bytes`; anything else would force a final
    whole-message copy)."""

    __slots__ = ("_out", "buf", "_view", "pos")

    def __init__(self, size: int):
        if size:
            self._out, self.buf = _alloc_uninit_bytes(size)
        else:
            self._out, self.buf = b"", np.empty(0, np.uint8)
        self._view = memoryview(self.buf)
        self.pos = 0

    def write(self, data) -> None:
        n = len(data)
        self._view[self.pos:self.pos + n] = data
        self.pos += n

    def write_array(self, payload: ArrayPayload) -> None:
        """Fused encode-and-store of an ArrayPayload: the codec (dtype
        cast / quantize / top-k pack) writes straight into the message
        buffer (no intermediate array/bytes)."""
        n = payload.nbytes
        payload.pack_into(self._view[self.pos:self.pos + n])
        self.pos += n

    def getvalue(self) -> bytes:
        assert self.pos == len(self._out), (self.pos, len(self._out))
        return self._out


def _varint_size(value: int) -> int:
    value &= _U64_MASK
    n = 1
    while value >= 0x80:
        value >>= 7
        n += 1
    return n


def _len_delimited_size(field_number: int, body_len: int) -> int:
    return (_varint_size(field_number << 3) + _varint_size(body_len)
            + body_len)


def _field_size(f: Field, value: Any) -> int:
    """Exact encoded byte count of one field, mirroring _encode_field's
    branching (incl. proto3 default elision) case for case — the two are
    kept adjacent and any divergence corrupts the stream (covered by the
    byte-identity tests vs protoc gencode in tests/test_wire_interop.py)."""
    kind = f.kind
    if f.repeated:
        if kind == "message":
            return sum(_len_delimited_size(f.number, item.encoded_size())
                       for item in value)
        if kind == "float":
            arr = np.asarray(value, dtype="<f4")
            if not arr.size:
                return 0
            return _len_delimited_size(f.number, 4 * arr.size)
        if kind in ("int32", "int64", "enum", "bool"):
            if not value:
                return 0
            body = sum(_varint_size(int(item)) for item in value)
            return _len_delimited_size(f.number, body)
        if kind == "string":
            return sum(_len_delimited_size(f.number,
                                           len(item.encode("utf-8")))
                       for item in value)
        raise TypeError(f"unsupported repeated kind {kind}")
    if kind in ("int32", "int64", "enum"):
        if not value:
            return 0
        return _varint_size(f.number << 3) + _varint_size(int(value))
    if kind == "bool":
        return _varint_size(f.number << 3) + 1 if value else 0
    if kind == "string":
        if not value:
            return 0
        return _len_delimited_size(f.number, len(value.encode("utf-8")))
    if kind == "bytes":
        if not value:
            return 0
        return _len_delimited_size(f.number, len(value))
    if kind == "float":
        if not value:
            return 0
        return _varint_size((f.number << 3) | WT_FIXED32) + 4
    if kind == "message":
        if value is None:
            return 0
        return _len_delimited_size(f.number, value.encoded_size())
    raise TypeError(f"unsupported kind {kind}")


def _encode_field(out: "_Writer", f: Field, value: Any) -> None:
    kind = f.kind
    if f.repeated:
        if kind == "message":
            for item in value:
                out.write(_tag(f.number, WT_LEN))
                out.write(encode_varint(item.encoded_size()))
                item.encode_into(out)
        elif kind == "float":
            arr = np.asarray(value, dtype="<f4")
            if arr.size:
                out.write(_tag(f.number, WT_LEN))
                out.write(encode_varint(4 * arr.size))
                out.write(memoryview(np.ascontiguousarray(arr)).cast("B"))
        elif kind in ("int32", "int64", "enum", "bool"):
            if value:
                body = bytearray()
                for item in value:
                    body += encode_varint(int(item))
                out.write(_tag(f.number, WT_LEN))
                out.write(encode_varint(len(body)))
                out.write(body)
        elif kind == "string":
            for item in value:
                data = item.encode("utf-8")
                out.write(_tag(f.number, WT_LEN))
                out.write(encode_varint(len(data)))
                out.write(data)
        else:
            raise TypeError(f"unsupported repeated kind {kind}")
        return

    if kind in ("int32", "int64", "enum"):
        if value:
            out.write(_tag(f.number, WT_VARINT))
            out.write(encode_varint(int(value)))
    elif kind == "bool":
        if value:
            out.write(_tag(f.number, WT_VARINT))
            out.write(b"\x01")
    elif kind == "string":
        if value:
            data = value.encode("utf-8")
            out.write(_tag(f.number, WT_LEN))
            out.write(encode_varint(len(data)))
            out.write(data)
    elif kind == "bytes":
        if value:
            out.write(_tag(f.number, WT_LEN))
            out.write(encode_varint(len(value)))
            if isinstance(value, ArrayPayload):
                out.write_array(value)
            else:
                out.write(value)
    elif kind == "float":
        if value:
            out.write(_tag(f.number, WT_FIXED32))
            out.write(struct.pack("<f", value))
    elif kind == "message":
        if value is not None:
            out.write(_tag(f.number, WT_LEN))
            out.write(encode_varint(value.encoded_size()))
            value.encode_into(out)
    else:
        raise TypeError(f"unsupported kind {kind}")


def _decode_field(msg: Message, buf: bytes, pos: int, f: Field, wire_type: int) -> int:
    kind = f.kind
    if f.repeated:
        if kind == "message":
            if wire_type != WT_LEN:
                raise ValueError(f"field {f.name}: bad wire type {wire_type}")
            length, pos = decode_varint(buf, pos)
            end = pos + length
            getattr(msg, f.name).append(
                f.message_type.decode(memoryview(buf)[pos:end]))
            return end
        if kind == "float":
            if wire_type == WT_LEN:  # packed
                length, pos = decode_varint(buf, pos)
                end = pos + length
                arr = np.frombuffer(buf, dtype="<f4", count=length // 4, offset=pos)
                existing = getattr(msg, f.name)
                setattr(msg, f.name,
                        arr if existing.size == 0 else np.concatenate([existing, arr]))
                return end
            if wire_type == WT_FIXED32:  # unpacked element
                val = struct.unpack_from("<f", buf, pos)[0]
                existing = getattr(msg, f.name)
                setattr(msg, f.name, np.append(existing, np.float32(val)))
                return pos + 4
            raise ValueError(f"field {f.name}: bad wire type {wire_type}")
        if kind in ("int32", "int64", "enum", "bool"):
            sign = _signed32 if kind == "int32" else _signed64
            if wire_type == WT_LEN:  # packed
                length, pos = decode_varint(buf, pos)
                end = pos + length
                lst = getattr(msg, f.name)
                while pos < end:
                    v, pos = decode_varint(buf, pos)
                    lst.append(bool(v) if kind == "bool" else sign(v))
                return end
            if wire_type == WT_VARINT:
                v, pos = decode_varint(buf, pos)
                getattr(msg, f.name).append(bool(v) if kind == "bool" else sign(v))
                return pos
            raise ValueError(f"field {f.name}: bad wire type {wire_type}")
        if kind == "string":
            length, pos = decode_varint(buf, pos)
            end = pos + length
            getattr(msg, f.name).append(str(buf[pos:end], "utf-8"))
            return end
        raise TypeError(f"unsupported repeated kind {kind}")

    if kind in ("int32", "int64", "enum"):
        v, pos = decode_varint(buf, pos)
        setattr(msg, f.name, _signed64(v) if kind == "int64" else _signed32(v))
        return pos
    if kind == "bool":
        v, pos = decode_varint(buf, pos)
        setattr(msg, f.name, bool(v))
        return pos
    if kind == "string":
        length, pos = decode_varint(buf, pos)
        end = pos + length
        setattr(msg, f.name, str(buf[pos:end], "utf-8"))
        return end
    if kind == "bytes":
        length, pos = decode_varint(buf, pos)
        end = pos + length
        # Small bytes fields (ids, names, digests) are copied eagerly:
        # a zero-copy memoryview slice would pin the ENTIRE RPC buffer
        # (possibly 100MB+) alive for as long as the field is retained,
        # and downstream consumers expect hashable `bytes`.  Tensor-sized
        # payloads stay zero-copy — their lifetime IS the buffer's
        # lifetime, and the copy is the cost we built this codec to avoid.
        raw = buf[pos:end]
        setattr(msg, f.name,
                bytes(raw) if length <= _BYTES_COPY_THRESHOLD else raw)
        return end
    if kind == "float":
        setattr(msg, f.name, struct.unpack_from("<f", buf, pos)[0])
        return pos + 4
    if kind == "message":
        length, pos = decode_varint(buf, pos)
        end = pos + length
        setattr(msg, f.name,
                f.message_type.decode(memoryview(buf)[pos:end]))
        return end
    raise TypeError(f"unsupported kind {kind}")


def serializer(cls: type[Message]) -> Callable[[Message], bytes]:
    """gRPC request/response serializer for a message class."""
    return lambda msg: msg.encode()


def deserializer(cls: type[Message]) -> Callable[[bytes], Message]:
    """gRPC request/response deserializer for a message class."""
    return cls.decode
