#!/usr/bin/env bash
# Converted-checkpoint flow: take a transformers GPT-2 checkpoint and run
# the WHOLE CLI suite on it — evaluate, fine-tune (full and LoRA, with a
# pipeline mesh), evaluate the fine-tune, generate, serve.  No
# intermediate export: every command takes the checkout directly and the
# conversion (models/hf.from_hf_gpt2) happens in-process.
#
#   bash examples/hf_checkpoint.sh [workdir]
#
# Uses a tiny randomly-initialized GPT-2 so the example runs anywhere in
# minutes; point HF_CKPT at a real checkout (e.g. a downloaded gpt2) to
# run the same flow at full scale.
set -euo pipefail
cd "$(dirname "$0")/.."
export PSDT_PLATFORM="${PSDT_PLATFORM:-cpu}"

WORK="${1:-/tmp/psdt_hf_example}"
STEPS="${STEPS:-30}"
mkdir -p "$WORK"

CORPUS="$WORK/corpus.txt"
if [ ! -s "$CORPUS" ]; then
  cat parameter_server_distributed_tpu/models/*.py > "$CORPUS"
fi

HF_CKPT="${HF_CKPT:-$WORK/hf_gpt2}"
if [ ! -d "$HF_CKPT" ]; then
  echo "== 0. make a tiny GPT-2 checkpoint (stand-in for a real checkout) =="
  python - "$HF_CKPT" <<'EOF'
import sys
import torch
import transformers

torch.manual_seed(0)
cfg = transformers.GPT2Config(vocab_size=512, n_positions=128, n_embd=64,
                              n_layer=2, n_head=2)
transformers.GPT2LMHeadModel(cfg).save_pretrained(sys.argv[1])
print(f"saved tiny GPT-2 to {sys.argv[1]}")
EOF
fi

echo "== 1. baseline evaluation of the raw converted checkpoint =="
python -m parameter_server_distributed_tpu.cli.eval_main \
  --hf-gpt2="$HF_CKPT" --data="$CORPUS" --batch=8 --steps=8

echo "== 2. fine-tune the converted model (the checkout IS the"
echo "      initializer; composes with --lora/--ema/pipe meshes) =="
python -m parameter_server_distributed_tpu.cli.train_main \
  --hf-gpt2="$HF_CKPT" --batch=8 --steps="$STEPS" --data="$CORPUS" \
  --optimizer=adamw --lr=3e-3 --ckpt-dir="$WORK/ft" --ckpt-every="$STEPS"

echo "== 3. or LoRA-fine-tune it on a 2-stage pipeline mesh (GPipe"
echo "      handles the GPT-2 arch; adapters are the only trainables)."
echo "      On this CPU host the 2 'chips' are virtual devices =="
XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=2" \
python -m parameter_server_distributed_tpu.cli.train_main \
  --hf-gpt2="$HF_CKPT" --batch=8 --steps="$STEPS" --data="$CORPUS" \
  --optimizer=adamw --lr=1e-2 --lora=4:8 \
  --mesh=pipe:2,data:1 --ckpt-dir="$WORK/lora" --ckpt-every="$STEPS"

echo "== 4. generate from the raw converted checkpoint and serve it."
echo "      (The tiny stand-in ships no tokenizer files, so this uses"
echo "      raw token ids; a real checkout serves --prompt text with"
echo "      its own tokenizer) =="
python -m parameter_server_distributed_tpu.cli.generate_main \
  --hf-gpt2="$HF_CKPT" --tokens=11,22,33 --max-new=24
printf '{"id": 1, "tokens": [11, 22, 33], "max_new": 16}\n' | \
  python -m parameter_server_distributed_tpu.cli.serve_main \
    --hf-gpt2="$HF_CKPT" --slots=2 --max-len=128

echo "example complete; artifacts in $WORK"
