#!/usr/bin/env bash
# The parameter-server topology the reference implements — coordinator +
# PS + workers as separate gRPC processes — run locally with this
# framework's extensions: an ELASTIC barrier (a worker joining mid-run
# widens the sync barrier without restarting the PS — the reference's
# scale script kills and restarts it, losing in-memory params) and the
# pst-status observability CLI.
#
#   bash examples/ps_cluster.sh
set -euo pipefail
cd "$(dirname "$0")/.."
export PSDT_PLATFORM="${PSDT_PLATFORM:-cpu}"
export PYTHONUNBUFFERED=1

PORT_BASE="${PORT_BASE:-15750}"
PS_PORT=$((PORT_BASE + 1))
COORD_PORT=$((PORT_BASE + 2))
WORK="$(mktemp -d)"
trap 'kill $(jobs -p) 2>/dev/null || true; rm -rf "$WORK"' EXIT

echo "== 1. parameter server: sync barrier, SGD lr 0.05, elastic width =="
python -m parameter_server_distributed_tpu.cli.ps_main \
  "127.0.0.1:${PS_PORT}" 2 5 --lr=0.05 --elastic \
  --coordinator="127.0.0.1:${COORD_PORT}" --ckpt-dir="$WORK" \
  >"$WORK/ps.log" 2>&1 &

echo "== 2. coordinator: registry + heartbeats + stale-worker reaper =="
python -m parameter_server_distributed_tpu.cli.coordinator_main \
  "127.0.0.1:${COORD_PORT}" "127.0.0.1:${PS_PORT}" \
  >"$WORK/coordinator.log" 2>&1 &

for i in $(seq 1 50); do
  grep -q "listening" "$WORK/ps.log" 2>/dev/null && \
  grep -q "listening" "$WORK/coordinator.log" 2>/dev/null && break
  sleep 0.2
done

echo "== 3. two workers training mnist_mlp (real grads, not the"
echo "      reference's 0.01 stub) =="
python -m parameter_server_distributed_tpu.cli.worker_main \
  "127.0.0.1:${COORD_PORT}" 0 8 127.0.0.1 15760 "" --batch=16 \
  >"$WORK/w0.log" 2>&1 &
W0=$!
python -m parameter_server_distributed_tpu.cli.worker_main \
  "127.0.0.1:${COORD_PORT}" 1 8 127.0.0.1 15761 "" --batch=16 \
  >"$WORK/w1.log" 2>&1 &
W1=$!

sleep 8
echo "== 4. elastic scale-up: worker 2 joins MID-RUN (barrier widens"
echo "      2 -> 3 live; no PS restart, no params lost) =="
python -m parameter_server_distributed_tpu.cli.worker_main \
  "127.0.0.1:${COORD_PORT}" 2 5 127.0.0.1 15762 "" --batch=16 \
  >"$WORK/w2.log" 2>&1 &
W2=$!

echo "== 5. cluster status while training (ListWorkers + sync state) =="
python -m parameter_server_distributed_tpu.cli.status_main \
  "127.0.0.1:${COORD_PORT}" || true

wait $W0 $W1 $W2
echo "== final status and worker tails =="
python -m parameter_server_distributed_tpu.cli.status_main \
  "127.0.0.1:${COORD_PORT}" || true
tail -n 2 "$WORK"/w*.log
ls "$WORK"/*.ckpt >/dev/null 2>&1 && echo "checkpoints written in $WORK"
echo "example complete"
