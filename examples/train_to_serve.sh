#!/usr/bin/env bash
# End-to-end walkthrough: train a byte-level LM on a real text corpus,
# checkpoint it, sample from the checkpoint, then serve it as a
# continuous-batching process with per-request sampling controls.
#
#   bash examples/train_to_serve.sh [workdir]
#
# Runs in a few minutes on a laptop CPU (PSDT_PLATFORM=cpu pins the host
# backend on machines where a TPU plugin hijacks JAX_PLATFORMS); on a TPU
# VM drop that export and raise STEPS/--batch.  Every command is the
# installed console-script surface — nothing here imports the package
# directly, so this is exactly what a user types.
set -euo pipefail
cd "$(dirname "$0")/.."

WORK="${1:-/tmp/psdt_example}"
STEPS="${STEPS:-60}"
mkdir -p "$WORK"
export PSDT_PLATFORM="${PSDT_PLATFORM:-cpu}"

# -- 1. corpus: this package's own source is a fine byte-level dataset
CORPUS="$WORK/corpus.txt"
if [ ! -s "$CORPUS" ]; then
  cat parameter_server_distributed_tpu/models/*.py > "$CORPUS"
fi

# -- 2. train small_lm on it (byte tokenizer: .txt is tokenized to a
#    cached shard on first use), checkpointing every 20 steps.
#    --mesh=data:1 keeps it single-device; on an 8-chip host try
#    --mesh=data:4,fsdp:2 — same command, sharded by GSPMD.
python -m parameter_server_distributed_tpu.cli.train_main \
  --model=small_lm --batch=8 --steps="$STEPS" \
  --data="$CORPUS" --optimizer=adamw --lr=3e-3 --schedule=cosine \
  --warmup=10 --ckpt-dir="$WORK/ckpt" --ckpt-every=20 --ckpt-keep=2 \
  --metrics="$WORK/metrics.jsonl"

# -- 3. sample from the latest checkpoint (greedy and nucleus)
python -m parameter_server_distributed_tpu.cli.generate_main \
  --model=small_lm --ckpt-dir="$WORK/ckpt" \
  --prompt="def forward" --max-new=48
python -m parameter_server_distributed_tpu.cli.generate_main \
  --model=small_lm --ckpt-dir="$WORK/ckpt" \
  --prompt="def forward" --max-new=48 --temperature=0.8 --top-p=0.9

# -- 4. serve it: JSONL line protocol on stdin/stdout.  One greedy
#    request, one hot-temperature request, one with a stop token (10 =
#    '\n' under the byte tokenizer) — all decoded in the same batch.
python -m parameter_server_distributed_tpu.cli.serve_main \
  --model=small_lm --ckpt-dir="$WORK/ckpt" --slots=4 <<'REQS'
{"id": "greedy", "prompt": "def forward", "max_new": 32}
{"id": "hot", "prompt": "def forward", "max_new": 32, "temperature": 0.9}
{"id": "one_line", "prompt": "def forward", "max_new": 32, "stop": [10]}
REQS

echo "example complete; artifacts in $WORK"
