#!/usr/bin/env bash
# Speculative continuous batching with a TRAINED draft: train the target
# (small_lm) and a 1-layer draft (tiny_lm) on the same corpus, then serve
# the target with draft/verify rounds — each request advances
# 1..draft_len+1 tokens per target forward at its measured acceptance
# rate, and greedy output stays token-exact vs plain serving.
#
#   bash examples/speculative_serving.sh [workdir]
set -euo pipefail
cd "$(dirname "$0")/.."
export PSDT_PLATFORM="${PSDT_PLATFORM:-cpu}"

WORK="${1:-/tmp/psdt_spec_example}"
STEPS="${STEPS:-60}"
mkdir -p "$WORK"

CORPUS="$WORK/corpus.txt"
if [ ! -s "$CORPUS" ]; then
  cat parameter_server_distributed_tpu/models/*.py > "$CORPUS"
fi

echo "== 1. train the target (small_lm) and the draft (tiny_lm) on the"
echo "      SAME corpus — acceptance comes from distribution match =="
python -m parameter_server_distributed_tpu.cli.train_main \
  --model=small_lm --batch=8 --steps="$STEPS" --data="$CORPUS" \
  --optimizer=adamw --lr=3e-3 --ckpt-dir="$WORK/target" --ckpt-every="$STEPS"
python -m parameter_server_distributed_tpu.cli.train_main \
  --model=tiny_lm --batch=8 --steps="$STEPS" --data="$CORPUS" \
  --optimizer=adamw --lr=3e-3 --ckpt-dir="$WORK/draft" --ckpt-every="$STEPS"

echo "== 2. serve the target with the draft (depth CAP 4 — the server"
echo "      ADAPTS the per-round depth from the measured accept rate,"
echo "      disabling speculation if this draft cannot pay on this host;"
echo "      add --no-adaptive-draft to pin the depth) =="
python -m parameter_server_distributed_tpu.cli.serve_main \
  --model=small_lm --ckpt-dir="$WORK/target" \
  --draft-model=tiny_lm --draft-ckpt="$WORK/draft" --draft-len=4 \
  --slots=4 <<'REQS'
{"id": "a", "prompt": "def forward", "max_new": 32}
{"id": "b", "prompt": "import jax", "max_new": 32}
REQS

echo "example complete; acceptance stats are logged by the server on exit"
