#!/usr/bin/env bash
# Parameter-efficient fine-tuning: pretrain a base LM, LoRA-fine-tune it
# with the base frozen (only rank-4 adapters train), then serve the
# adapted model both ways — merged on load by the serving CLI, and as a
# dense export.
#
#   bash examples/finetune_lora.sh [workdir]
set -euo pipefail
cd "$(dirname "$0")/.."
export PSDT_PLATFORM="${PSDT_PLATFORM:-cpu}"

WORK="${1:-/tmp/psdt_lora_example}"
STEPS="${STEPS:-40}"
mkdir -p "$WORK"

CORPUS="$WORK/corpus.txt"
if [ ! -s "$CORPUS" ]; then
  cat parameter_server_distributed_tpu/models/*.py > "$CORPUS"
fi

echo "== 1. pretrain the base model (dense, all parameters) =="
python -m parameter_server_distributed_tpu.cli.train_main \
  --model=small_lm --batch=8 --steps="$STEPS" --data="$CORPUS" \
  --optimizer=adamw --lr=3e-3 --ckpt-dir="$WORK/base" --ckpt-every="$STEPS"

echo "== 2. LoRA fine-tune FROM that checkpoint: rank-4 adapters on the"
echo "      attention q/v projections are the only trainable parameters"
echo "      (the log line confirms the frozen base) =="
python -m parameter_server_distributed_tpu.cli.train_main \
  --model=small_lm --batch=8 --steps="$STEPS" --data="$CORPUS" \
  --optimizer=adamw --lr=1e-2 --lora=4:8 --init-ckpt-dir="$WORK/base" \
  --ckpt-dir="$WORK/lora" --ckpt-every="$STEPS"

echo "== 3. serve the adapted model: the CLI folds the adapters into"
echo "      dense weights on load (--lora-alpha must match training) =="
python -m parameter_server_distributed_tpu.cli.generate_main \
  --model=small_lm --ckpt-dir="$WORK/lora" --lora-alpha=8 \
  --prompt="def forward" --max-new=48

echo "== 4. or export a permanent dense checkpoint (merge_lora) =="
python - "$WORK" <<'EOF'
import sys
from parameter_server_distributed_tpu.checkpoint import codec, sharded
from parameter_server_distributed_tpu.models.lora import merge_lora

import numpy as np

work = sys.argv[1]
step, state = sharded.restore_latest(f"{work}/lora")
params = state["params"] if isinstance(state, dict) else state.params
merged = {k: np.asarray(v) for k, v in merge_lora(params, alpha=8.0).items()}
codec.save(f"{work}/merged.ckpt", epoch=0, iteration=step, params=merged)
print(f"dense export: {work}/merged.ckpt ({len(merged)} tensors)")
EOF
python -m parameter_server_distributed_tpu.cli.generate_main \
  --model=small_lm --ckpt="$WORK/merged.ckpt" \
  --prompt="def forward" --max-new=24

echo "example complete; artifacts in $WORK"
